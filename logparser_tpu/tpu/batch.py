"""The batch parsing API: ``TpuBatchParser.parse_batch(lines) -> BatchResult``.

This is the product hot path (SURVEY §7: "compile the LogFormat to a static
field-extraction program, execute it over [B, L] uint8 batches on TPU").
Strings never leave the device as Python strings: string-typed fields are
(offset, length) span columns into the input buffer; numeric/epoch fields are
int32-limb columns decoded on device and combined to int64 on the host.

The split program AND all requested post-stages (numeric parse, timestamp ->
epoch, first-line split) trace into ONE jitted function per parser — a single
fused XLA computation per (B, L) shape bucket; batch and line length are both
padded to a bounded set of length buckets so recompilation is bounded.

Multi-format parsers run EVERY registered format's split automaton in the
same fused device computation and pick the per-line winner by registration
priority (the vectorized version of HttpdLogFormatDissector.java:174-204's
active/fallback switching — see pipeline.FormatUnit).  The host oracle (the
exact per-line engine in logparser_tpu.core/httpd) handles lines the
optimistic device split rejects and requested fields outside the winning
format's device-resolvable set (wildcards, URI repair, cookies, ...), so the
combined result is bit-exact with the reference semantics at batch
throughput for the common case.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

import os
import threading

from ..core.casts import Cast
from ..core.exceptions import DissectionFailure, OracleEngineError
from ..core.fields import cleanup_field_value

import logging as _logging

_LOG = _logging.getLogger(__name__)
from ..httpd.parser import HttpdLoglineParser
from .pipeline import (
    FieldPlan,
    FormatUnit,
    PackedLayout,
    assign_row_offsets,
    build_units_jnp_fn,
)
from .program import (
    CS_CLF_DIGITS,
    CS_DIGITS,
    DeviceProgram,
    UnsupportedFormatError,
    compile_device_program,
)
from .runtime import encode_batch
from . import postproc, timefields

# Back-compat alias (plan resolution lives here; packing in pipeline.py).
_FieldPlan = FieldPlan

# Octet -> string vocab for vectorized dotted-quad formatting.
_OCTET_STRINGS = np.array([str(i) for i in range(256)], dtype=object)


def _apply_setter_casts(value, has_long: bool, has_double: bool):
    """LONG-then-DOUBLE setter-cast fallthrough (the reference's
    setter-signature dispatch, Parser.store's Long/Double/String setter
    preference).  SINGLE home for the ladder — used by both
    _coerce_casts (remapped sub-dissection deliveries) and the oracle
    delivery plan, which must type identical values identically."""
    if has_long:
        try:
            return int(value)
        except (TypeError, ValueError):
            pass
    if has_double:
        try:
            return float(value)
        except (TypeError, ValueError):
            pass
    return value


def _fix_uri_part(value: str, mode: str) -> str:
    """Per-row URI micro-materialization for device `fix` rows: the exact
    host repair semantics, applied to one sub-span instead of re-parsing
    the whole line (HttpUriDissector.java:111-121 encode, :166-167
    %-repair; java.net.URI path/userinfo decode).  The encode step is
    byte-local, so running it on the sub-span equals running it on the
    whole URI; the %-repair runs twice like the host (overlaps)."""
    from ..dissectors.uri import (
        _BAD_ESCAPE_PATTERN,
        _encode_bad_uri_chars,
        _percent_decode,
    )

    value = _encode_bad_uri_chars(value)
    value = _BAD_ESCAPE_PATTERN.sub(r"%25\1", value)
    value = _BAD_ESCAPE_PATTERN.sub(r"%25\1", value)
    if mode in ("path", "userinfo"):
        value = _percent_decode(value)
    return value


# Hex digit -> value (255 = not a hex digit), for the vectorized CSR
# value decode below.
_HEX_VAL = np.full(256, 255, dtype=np.uint8)
for _c in b"0123456789":
    _HEX_VAL[_c] = _c - ord("0")
for _c in b"abcdef":
    _HEX_VAL[_c] = _c - ord("a") + 10
for _c in b"ABCDEF":
    _HEX_VAL[_c] = _c - ord("A") + 10
del _c

# Label-bounded field names for host_field_lines_total{field}: the first
# _MAX_FIELD_LABELS distinct requested fields keep their own label, the
# tail collapses to "overflow" (same discipline as the front's key/tenant
# labels) so a hostile field list can't explode the registry.
_MAX_FIELD_LABELS = 64
_FIELD_LABEL_POOL: set = set()
_FIELD_LABEL_LOCK = threading.Lock()


def _bounded_field_label(fid: str) -> str:
    with _FIELD_LABEL_LOCK:
        if fid in _FIELD_LABEL_POOL:
            return fid
        if len(_FIELD_LABEL_POOL) < _MAX_FIELD_LABELS:
            _FIELD_LABEL_POOL.add(fid)
            return fid
        return "overflow"


def _qs_value_decode(bts, off):
    """Vectorized '+'/percent decode of concatenated value segments.

    ``bts`` is the raw bytes of n segments back to back; ``off`` the
    [n+1] int64 segment offsets.  Per byte: '+' -> 0x20, '%' followed by
    two same-segment hex digits -> the decoded byte (the two digits are
    consumed), anything else verbatim — the left-to-right rule of
    repair-then-URLDecode on a query value ('%' is not a hex digit, so
    escape starts can never overlap and the sequential scan vectorizes
    exactly).  Returns ``(decoded bytes, decoded offsets, bad)`` where
    ``bad[k]`` marks segments the rule does NOT cover for DIRECT token
    captures: a '%' without two in-segment hex digits (the un-repaired
    host decoder may chop it, raise ValueError, or read a %uXXXX UTF-16
    escape) or a raw byte >= 0x80 (URI-chain segments are clean ASCII by
    the split discipline; direct captures are not)."""
    n = len(off) - 1
    total = int(off[-1])
    if total == 0:
        return (np.zeros(0, dtype=np.uint8), np.zeros(n + 1, dtype=np.int64),
                np.zeros(n, dtype=bool))
    lens = np.diff(off)
    seg_id = np.repeat(np.arange(n, dtype=np.int64), lens)
    seg_end = np.repeat(off[1:], lens)
    pos = np.arange(total, dtype=np.int64)
    hexv = _HEX_VAL[bts]
    is_hex = hexv < 16
    is_pct = bts == 0x25
    i1 = np.minimum(pos + 1, total - 1)
    i2 = np.minimum(pos + 2, total - 1)
    start = is_pct & (pos + 2 < seg_end) & is_hex[i1] & is_hex[i2]
    consumed = np.zeros(total, dtype=bool)
    consumed[1:] |= start[:-1]
    consumed[2:] |= start[:-2]
    out = np.where(bts == 0x2B, np.uint8(0x20), bts)
    out = np.where(
        start, (hexv[i1].astype(np.uint8) << 4) | hexv[i2], out
    ).astype(np.uint8)
    bad_b = (is_pct & ~start) | (bts >= 0x80)
    bad = np.zeros(n, dtype=bool)
    if bad_b.any():
        bad = np.bincount(seg_id[bad_b], minlength=n) > 0
    keep = ~consumed
    new_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(seg_id[keep], minlength=n), out=new_off[1:])
    return out[keep], new_off, bad


def _latin1_to_utf8(bts, off):
    """Transcode decoded (latin-1 semantics) segment bytes to UTF-8 so
    they can ride the wildcard flat value buffer (whose consumers decode
    UTF-8): each byte < 0x80 passes through, each byte >= 0x80 expands
    to the two-byte UTF-8 form of U+0080..U+00FF."""
    hi = bts >= 0x80
    if not hi.any():
        return bts, off
    n = len(off) - 1
    lens = np.diff(off)
    seg_id = np.repeat(np.arange(n, dtype=np.int64), lens)
    width = 1 + hi.astype(np.int64)
    dst = np.cumsum(width) - width
    out = np.empty(int(dst[-1] + width[-1]) if len(dst) else 0,
                   dtype=np.uint8)
    out[dst] = np.where(hi, 0xC0 | (bts >> 6), bts)
    out[dst[hi] + 1] = 0x80 | (bts[hi] & 0x3F)
    extra = np.bincount(seg_id[hi], minlength=n)
    new_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens + extra, out=new_off[1:])
    return out, new_off


def _seg_scatter(dst, dst_off, src, src_off, lens):
    """Copy n variable-length segments src[src_off[k]:+lens[k]] ->
    dst[dst_off[k]:+lens[k]] with one gather/scatter pair."""
    total = int(lens.sum())
    if total == 0:
        return
    cum = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=cum[1:])
    ar = np.arange(total, dtype=np.int64)
    dst[np.repeat(dst_off - cum[:-1], lens) + ar] = (
        src[np.repeat(src_off - cum[:-1], lens) + ar]
    )


class _CollectingRecord:
    """Host-fallback record capturing every delivered value by field id."""

    def __init__(self) -> None:
        self.values: Dict[str, Any] = {}

    def set_value(self, name: str, value) -> None:
        self.values[name] = value


# ---------------------------------------------------------------------------
# Parallel oracle fallback: the per-line engine is pure Python, so large
# fallback sets (hostile batches, host-only fields) are fanned out over a
# persistent spawn pool — each worker holds ONE unpickled oracle parser (the
# reference's serialize-config-to-workers distribution contract, SURVEY §3.4,
# applied to the fallback path).
# ---------------------------------------------------------------------------

_WORKER_PARSER = None


def _oracle_worker_init(blob: bytes) -> None:
    global _WORKER_PARSER
    import pickle

    _WORKER_PARSER = pickle.loads(blob)
    _WORKER_PARSER.assemble_dissectors()


def _values_of(rec):
    """parse_many result -> delivery value: the record's values dict, or
    the None / OracleEngineError verdict passed through unchanged."""
    if rec is None or isinstance(rec, OracleEngineError):
        return rec
    return rec.values


def _oracle_worker_run(lines: List[str]) -> List[Optional[Dict[str, Any]]]:
    return [
        _values_of(rec)
        for rec in _WORKER_PARSER.parse_many(lines, _CollectingRecord)
    ]


class _LazyWildcard:
    """Override mapping for wildcard (``.*``) CSR fields.

    The flat CSR buffers (rows, per-segment name/value byte runs) are kept
    as-is; the per-row Python dicts the ``to_pylist`` contract requires
    materialize on first dict-style access.  The Arrow bridge reads the
    flat buffers directly (``to_arrow_map``) and never pays the per-row
    build.  ``eager`` holds dicts delivered individually (slow-path rows,
    oracle fallback); it always wins over chunk data for the same row.
    """

    __slots__ = ("eager", "chunks", "_dense", "dropped")

    def __init__(self) -> None:
        self.eager: Dict[int, Any] = {}
        # (vrows, seg_row, name_bytes, name_off, val_bytes, val_off, high)
        self.chunks: List[tuple] = []
        self._dense: Optional[Dict[int, Any]] = None
        # Tombstones: rows popped by the caller (csr_failed invalidation).
        # A row can be chunk-delivered by one CSR group and failed by
        # ANOTHER group on the same line, so pop must shadow chunk data
        # too, not just `eager`.
        self.dropped: set = set()

    def add_chunk(self, vrows, seg_row, nb, non, vb, nov, seg_high) -> None:
        self.chunks.append((vrows, seg_row, nb, non, vb, nov, seg_high))
        self._dense = None

    def _materialize(self) -> Dict[int, Any]:
        if self._dense is None:
            dense: Dict[int, Any] = {}
            for vrows, seg_row, nb, non, vb, nov, _hi in self.chunks:
                for r in vrows.tolist():
                    dense[r] = {}
                rl = seg_row.tolist()
                for j in range(len(rl)):
                    name = (
                        nb[non[j] : non[j + 1]]
                        .decode("utf-8", "replace").lower()
                    )
                    dense[rl[j]][name] = vb[nov[j] : nov[j + 1]].decode(
                        "utf-8", "replace"
                    )
            dense.update(self.eager)
            for i in self.dropped:
                dense.pop(i, None)
            self._dense = dense
        return self._dense

    def __contains__(self, i) -> bool:
        return i in self._materialize()

    def __getitem__(self, i):
        return self._materialize()[i]

    def __setitem__(self, i, value) -> None:
        self.eager[i] = value
        self.dropped.discard(i)
        if self._dense is not None:
            self._dense[i] = value

    def pop(self, i, default=None):
        self.dropped.add(i)
        if self._dense is not None:
            self._dense.pop(i, None)
        return self.eager.pop(i, default)

    def __bool__(self) -> bool:
        return bool(self.eager) or any(
            len(c[0]) for c in self.chunks
        ) or bool(self._dense)

    def sliced(self, start: int, stop: int) -> "_LazyWildcard":
        """Row-window copy for :meth:`BatchResult.slice`: eager rows and
        tombstones rebase to slice-local indices; each flat chunk is
        filtered to the window's segments with its byte runs re-packed —
        the SAME one-chunk construction a solo parse of those rows would
        have produced, so ``to_arrow_map``'s fast path (and its output
        bytes) are preserved across slicing."""
        out = _LazyWildcard()
        out.eager = {
            i - start: v for i, v in self.eager.items() if start <= i < stop
        }
        out.dropped = {
            i - start for i in self.dropped if start <= i < stop
        }
        for vrows, seg_row, nb, non, vb, nov, seg_high in self.chunks:
            vrows = np.asarray(vrows, dtype=np.int64)
            seg_row = np.asarray(seg_row, dtype=np.int64)
            vsel = (vrows >= start) & (vrows < stop)
            ssel = (seg_row >= start) & (seg_row < stop)
            if not vsel.any() and not ssel.any():
                continue
            name_lens = np.diff(np.asarray(non, dtype=np.int64))
            val_lens = np.diff(np.asarray(nov, dtype=np.int64))
            nb_np = np.frombuffer(nb, dtype=np.uint8)
            vb_np = np.frombuffer(vb, dtype=np.uint8)
            new_non = np.zeros(int(ssel.sum()) + 1, dtype=np.int64)
            np.cumsum(name_lens[ssel], out=new_non[1:])
            new_nov = np.zeros(int(ssel.sum()) + 1, dtype=np.int64)
            np.cumsum(val_lens[ssel], out=new_nov[1:])
            out.add_chunk(
                vrows[vsel] - start,
                seg_row[ssel] - start,
                nb_np[np.repeat(ssel, name_lens)].tobytes(),
                new_non,
                vb_np[np.repeat(ssel, val_lens)].tobytes(),
                new_nov,
                np.asarray(seg_high, dtype=bool)[ssel],
            )
        return out

    def to_arrow_map(self, B: int):
        """pyarrow MapArray built straight from the flat buffers; None when
        this needs the exact dict path (multi-chunk/multi-format results,
        non-ASCII names whose str.lower() differs from the byte fold,
        duplicate names within a row — the dict contract collapses those).
        Individually-delivered rows (``eager``: decode/repair/oracle rows)
        and popped rows (``dropped``) are PATCHED into the flat
        construction rather than disabling it — a single %-escaped value
        in a big batch must not cost the whole column its fast path."""
        if self._dense is not None or len(self.chunks) != 1:
            return None
        if len(self.eager) > max(64, B // 32):
            return None  # heavy fallback traffic: splicing stops paying
        import pyarrow as pa

        vrows, seg_row, nb, non, vb, nov, seg_high = self.chunks[0]
        seg_row = np.asarray(seg_row, dtype=np.int64)
        seg_high = np.asarray(seg_high, dtype=bool)
        n_seg = len(seg_row)
        name_lens = np.diff(non)
        val_lens = np.diff(nov)
        nb_np = np.frombuffer(nb, dtype=np.uint8)
        vb_np = np.frombuffer(vb, dtype=np.uint8)
        upper = (nb_np >= 0x41) & (nb_np <= 0x5A)
        folded = np.where(upper, nb_np | 0x20, nb_np)

        # Rows whose chunk segments must not be emitted: individually
        # delivered (eager wins) or popped.  Filter BEFORE the bail-out
        # checks so a shadowed row's segments (e.g. duplicate names on an
        # oracle-overridden line) cannot cost the column its fast path.
        shadow = set(self.dropped)
        shadow.update(self.eager)
        if shadow:
            shadow_arr = np.fromiter(shadow, dtype=np.int64)
            seg_keep = ~np.isin(seg_row, shadow_arr)
            if not seg_keep.all():
                byte_keep_n = np.repeat(seg_keep, name_lens)
                byte_keep_v = np.repeat(seg_keep, val_lens)
                folded = folded[byte_keep_n]
                vb_np = vb_np[byte_keep_v]
                seg_row = seg_row[seg_keep]
                seg_high = seg_high[seg_keep]
                name_lens = name_lens[seg_keep]
                val_lens = val_lens[seg_keep]
                n_seg = len(seg_row)

        if bool(seg_high.any()):
            return None
        # One shared pair of cumulative offsets over the filtered segment
        # lens (used by the duplicate check, the eager splice, and — when
        # no splice mutates the lens — the final StringArray offsets).
        nb_off = np.zeros(n_seg + 1, dtype=np.int64)
        np.cumsum(name_lens, out=nb_off[1:])
        vb_off = np.zeros(n_seg + 1, dtype=np.int64)
        np.cumsum(val_lens, out=vb_off[1:])
        if n_seg:
            # Duplicate-name detection by signature (row, len, sum, first,
            # last byte) over the FOLDED bytes — the emitted keys are
            # folded, so "A"/"a" must count as duplicates.  Any collision
            # — including a false positive — bails to the dict path,
            # which dedups exactly.
            sums = np.add.reduceat(folded.astype(np.int64), nb_off[:-1])
            sig = np.stack([
                seg_row, name_lens, sums,
                folded[nb_off[:-1]].astype(np.int64),
                folded[nb_off[1:] - 1].astype(np.int64),
            ])
            if np.unique(sig, axis=1).shape[1] != n_seg:
                return None

        counts = np.zeros(B, dtype=np.int64)
        left = np.searchsorted(seg_row, vrows, side="left")
        right = np.searchsorted(seg_row, vrows, side="right")
        counts[vrows] = right - left
        covered = np.zeros(B, dtype=bool)
        covered[vrows] = True
        for i in self.dropped:
            if 0 <= i < B:
                covered[i] = False
                counts[i] = 0

        # Splice the eager rows' items into row order (few rows: python
        # per ROW, still vectorized per segment everywhere else).
        spliced = False
        if self.eager:
            cut_bytes_n = cut_bytes_v = cut_seg = 0
            inserts = []
            for i in sorted(self.eager):
                if not (0 <= i < B) or i in self.dropped:
                    # Dropped wins over eager — matching _materialize's
                    # update-then-pop order.
                    continue
                d = self.eager[i]
                if d is None:
                    covered[i] = False
                    counts[i] = 0
                    continue
                covered[i] = True
                counts[i] = len(d)
                keys_b = [str(k).encode("utf-8") for k in d.keys()]
                vals_b = [str(v).encode("utf-8") for v in d.values()]
                inserts.append((i, keys_b, vals_b))
            if inserts:
                spliced = True
                name_pieces, val_pieces = [], []
                len_pieces_n, len_pieces_v = [], []
                for i, keys_b, vals_b in inserts:
                    at = int(np.searchsorted(seg_row, i, side="left"))
                    name_pieces.append(folded[cut_bytes_n:int(nb_off[at])])
                    val_pieces.append(vb_np[cut_bytes_v:int(vb_off[at])])
                    len_pieces_n.append(name_lens[cut_seg:at])
                    len_pieces_v.append(val_lens[cut_seg:at])
                    if keys_b:
                        name_pieces.append(
                            np.frombuffer(b"".join(keys_b), dtype=np.uint8)
                        )
                        val_pieces.append(
                            np.frombuffer(b"".join(vals_b), dtype=np.uint8)
                        )
                        len_pieces_n.append(
                            np.array([len(k) for k in keys_b], dtype=np.int64)
                        )
                        len_pieces_v.append(
                            np.array([len(v) for v in vals_b], dtype=np.int64)
                        )
                    cut_bytes_n, cut_bytes_v, cut_seg = (
                        int(nb_off[at]), int(vb_off[at]), at
                    )
                name_pieces.append(folded[cut_bytes_n:])
                val_pieces.append(vb_np[cut_bytes_v:])
                len_pieces_n.append(name_lens[cut_seg:])
                len_pieces_v.append(val_lens[cut_seg:])
                folded = np.concatenate(name_pieces)
                vb_np = np.concatenate(val_pieces)
                name_lens = np.concatenate(len_pieces_n)
                val_lens = np.concatenate(len_pieces_v)
                n_seg = len(name_lens)

        if spliced:  # the splice changed the lens: recompute offsets
            non32 = np.zeros(n_seg + 1, dtype=np.int64)
            np.cumsum(name_lens, out=non32[1:])
            nov32 = np.zeros(n_seg + 1, dtype=np.int64)
            np.cumsum(val_lens, out=nov32[1:])
        else:
            non32, nov32 = nb_off, vb_off
        if int(non32[-1]) > np.iinfo(np.int32).max or int(
            nov32[-1]
        ) > np.iinfo(np.int32).max:
            return None
        offsets64 = np.zeros(B + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets64[1:])
        offsets = offsets64.astype(np.int32)
        mask = np.concatenate([~covered, [False]])
        try:
            keys = pa.StringArray.from_buffers(
                n_seg,
                pa.py_buffer(non32.astype(np.int32)),
                pa.py_buffer(np.ascontiguousarray(folded)),
            )
            items = pa.StringArray.from_buffers(
                n_seg,
                pa.py_buffer(nov32.astype(np.int32)),
                pa.py_buffer(np.ascontiguousarray(vb_np)),
            )
            arr = pa.MapArray.from_arrays(
                pa.array(offsets, type=pa.int32(), mask=mask), keys, items
            )
            arr.validate(full=True)  # UTF-8 check happens here
        except (pa.lib.ArrowException, TypeError, ValueError):
            # Anything the flat construction cannot express exactly falls
            # back to the dict path (which is always correct).
            return None
        return arr


class _BlobLines:
    """Lazy per-line view of a newline-delimited blob: the batch ingest
    path never builds a Python line list — rows materialize as bytes only
    when indexed (oracle-rescued rows, debugging).  Framing semantics are
    exactly :func:`logparser_tpu.native.encode_blob`'s: a final empty
    segment after a trailing newline is dropped and one trailing ``\\r``
    per line is stripped.

    ``blob`` may be bytes or any 1-D uint8 buffer (the feeder ring hands
    a shared-memory slot VIEW straight through — the payload is never
    copied unless a row is actually rescued)."""

    __slots__ = ("_blob", "_bytes", "_n", "_starts", "_ends")

    def __init__(self, blob):
        self._bytes = isinstance(blob, (bytes, bytearray))
        if not self._bytes:
            blob = np.frombuffer(blob, dtype=np.uint8)
        self._blob = blob
        # Cheap length only (one C-level count); the per-line index
        # arrays build lazily on first access — almost no row ever
        # materializes (only oracle-rescued ones).
        if self._bytes:
            from ..feeder.worker import _count_lines

            # The single home of the trailing-newline counting rule
            # (the ndarray branch below is its vectorized twin).
            self._n = _count_lines(blob)
        else:
            if not len(blob):
                self._n = 0
            else:
                nl = int(np.count_nonzero(blob == 0x0A))
                self._n = nl if blob[-1] == 0x0A else nl + 1
        self._starts = None
        self._ends = None

    def _index(self):
        if self._starts is None:
            blob = self._blob
            arr = (np.frombuffer(blob, dtype=np.uint8)
                   if self._bytes else blob)
            nl = np.flatnonzero(arr == 0x0A)
            starts = np.concatenate([[0], nl + 1]).astype(np.int64)
            ends = np.concatenate([nl, [len(blob)]]).astype(np.int64)
            if len(arr) and arr[-1] == 0x0A:
                starts = starts[:-1]
                ends = ends[:-1]
            cr = (arr[np.maximum(ends - 1, 0)] == 0x0D) & (ends > starts)
            self._starts = starts
            self._ends = ends - cr
        return self._starts, self._ends

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        starts, ends = self._index()
        raw = self._blob[starts[i]: ends[i]]
        return raw if self._bytes else raw.tobytes()

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class _SliceLines:
    """Row-window view of a parent lines sequence (list or
    :class:`_BlobLines`): the lines handle a sliced :class:`BatchResult`
    carries.  Rows materialize lazily through the parent — a blob-backed
    parent still only ever materializes the rows somebody indexes."""

    __slots__ = ("_parent", "_start", "_n")

    def __init__(self, parent, start: int, n: int):
        self._parent = parent
        self._start = start
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return self._parent[self._start + i]

    def __iter__(self):
        for i in range(self._n):
            yield self[i]


def _release_stream_item(item) -> None:
    """Give a stream item's ring slot back (zero-copy feeder batches);
    plain batches and line lists have no lease (no-op / absent)."""
    release = getattr(item, "release", None)
    if release is not None:
        release()


def _raw_line_bytes(line) -> bytes:
    """One line as ingested bytes — :meth:`BatchResult.raw_line`'s
    conversion for paths that carry no result object (the aggregate
    reject ledger)."""
    if isinstance(line, bytes):
        return line
    if isinstance(line, (bytearray, memoryview)):
        return bytes(line)
    return str(line).encode("utf-8", errors="surrogateescape")


class BatchResult:
    """Columnar parse result over one batch."""

    def __init__(self, lines, buf, lengths, valid, columns, overrides, good, bad,
                 format_index=None, oracle_rows=0, packed=None,
                 device_views=None, dirty_rows=None, assembly_pool=None):
        # Shared delivery-path worker pool (tpu/hostpool.py): to_arrow's
        # per-column assembly and the native memcpy fan-outs read their
        # parallelism from it.  None = serial (the pre-pool behavior).
        self.assembly_pool = assembly_pool
        # Device-emitted Arrow view rows: `packed` holds ONLY the trailing
        # view block (4 int32 rows per span field, copied out of the
        # device fetch); device_views maps field_id -> row index of its
        # merged span word inside that block (+1..+3 = LE-packed first-12
        # bytes); the Arrow bridge interleaves them natively.  dirty_rows
        # marks rows (overflow-truncated lines) whose device views must
        # be zeroed/patched on host.
        self.packed = packed
        self.device_views = device_views or {}
        self.dirty_view_rows = (
            dirty_rows if dirty_rows is not None
            else np.empty(0, dtype=np.int64)
        )
        # Lines the host oracle had to visit (device-invalid lines plus
        # lines whose winning format left requested fields device-unresolved)
        # — the number bench.py reports as oracle_fraction.
        self.oracle_rows = oracle_rows
        self._lines = lines
        self.buf = buf                  # np [B, L] uint8
        self.lengths = lengths
        self.valid = valid              # np [B] bool: overall line validity
        self._columns = columns         # field_id -> dict of arrays (per kind)
        self._overrides = overrides     # field_id -> {row: python value}
        self.lines_read = len(lines)
        self.good_lines = good
        self.bad_lines = bad
        # Rescue composition (filled by the materializer): routed-line
        # counts by reject reason and the wall seconds rescue added.
        self.rescue_reasons: Dict[str, int] = {}
        self.rescue_wall_s: float = 0.0
        # Lines the device claimed THROUGH the escape-parity mask (their
        # quoted-field split skipped a backslash-escaped separator
        # occurrence): the round-18 class that used to route to the host
        # rescue.  Filled by the materializer from the winning unit's
        # ESC_QUOTE_BIT; mirrors device_escaped_quote_lines_total.
        self.escaped_quote_rows: int = 0
        # Per-row reject ledger (filled by the materializer): row ->
        # stable reason ("implausible" | "oracle_reject" |
        # "oracle_error") for every row whose ``valid`` ended False —
        # the jobs reject channel reads it to build per-line error
        # tables instead of silently dropping bad lines.
        self.reject_reasons: Dict[int, str] = {}
        # Sorted row ids the host oracle visited (set by the
        # materializer; slices rebase it) — lets :meth:`slice` report the
        # EXACT per-window oracle_rows a solo parse would have counted.
        self.oracle_row_ids: Optional[np.ndarray] = None
        # Per-line index of the registered format that matched on device
        # (-1 = decided by the host oracle / no device match).  The columnar
        # analogue of the reference's "Switched to LogFormat" signal
        # (HttpdLogFormatDissector.java:162-165).
        self.format_index = (
            format_index
            if format_index is not None
            else np.full(self.lines_read, -1, dtype=np.int64)
        )
        self._ascii_only: Optional[bool] = None

    @property
    def ascii_only(self) -> bool:
        """True when every byte of the batch buffer is < 0x80 — then any
        gathered span is trivially valid UTF-8 and the Arrow bridge can
        skip its per-column validate pass.  One SIMD max over the buffer,
        computed lazily and cached for the batch."""
        if self._ascii_only is None:
            B = self.lines_read
            self._ascii_only = bool(
                B == 0 or int(self.buf[:B].max(initial=0)) < 0x80
            )
        return self._ascii_only

    def raw_line(self, i: int) -> bytes:
        """The raw bytes of line ``i`` exactly as ingested (lazy under
        blob ingest — only requested rows materialize).  String inputs
        encode UTF-8; the jobs reject channel stores these verbatim."""
        line = self._lines[i]
        if isinstance(line, bytes):
            return line
        if isinstance(line, (bytearray, memoryview)):
            return bytes(line)
        return str(line).encode("utf-8", errors="surrogateescape")

    def field_ids(self) -> List[str]:
        return list(self._columns.keys())

    def column(self, field_id: str) -> Dict[str, np.ndarray]:
        """Raw column arrays: spans have starts/ends; numerics have values +
        null mask."""
        return self._columns[cleanup_field_value(field_id)]

    def to_pylist(self, field_id: str) -> List[Any]:
        """Materialize one column as Python values (strings/ints/None)."""
        field_id = cleanup_field_value(field_id)
        col = self._columns[field_id]
        overrides = self._overrides.get(field_id, {})
        out: List[Any] = []
        kind = col["kind"]
        for i in range(self.lines_read):
            if i in overrides:
                out.append(overrides[i])
                continue
            if not self.valid[i] or not col["ok"][i]:
                out.append(None)
                continue
            if kind == "numeric":
                if col["null"][i]:
                    # Per-line CLF-zero semantics: the format that won the
                    # line decides whether '-' means 0 or null.
                    out.append(0 if col["null_zero"][i] else None)
                else:
                    out.append(int(col["values"][i]))
            elif kind == "obj":
                v = col["values"][i]
                out.append(v.item() if isinstance(v, np.generic) else v)
            else:
                if col["null"][i]:
                    # Device-computed null: CLF '-' token captures and
                    # undelivered URI parts.
                    out.append(None)
                    continue
                start, end = int(col["starts"][i]), int(col["ends"][i])
                raw = bytes(self.buf[i, start:end])
                if col.get("amp") is not None and col["amp"][i] and raw[:1] == b"?":
                    raw = b"&" + raw[1:]  # the ?& query normalization
                value = raw.decode("utf-8", errors="replace")
                if col.get("fix") is not None and col["fix"][i]:
                    value = _fix_uri_part(value, col["fix_mode"])
                out.append(value)
        return out

    def to_dict(self) -> Dict[str, List[Any]]:
        return {fid: self.to_pylist(fid) for fid in self._columns}

    def span_bytes(self, field_id: str, include_fix: bool = False,
                   threads: int = 0):
        """Flat-bytes view of a device span column for non-Arrow consumers:
        (data uint8, offsets int64, valid bool) — row r's raw value is
        ``data[offsets[r]:offsets[r+1]]`` when valid[r].  Uses the native
        threaded gather (numpy fallback inside).  Returns None when the
        column has host overrides or repair (`fix`) rows — unless
        ``include_fix`` (the Arrow bridge gathers repair rows raw and
        splices the repaired values afterwards); override columns always
        need the per-row path (:meth:`to_pylist`).  ``threads`` caps the
        native gather's fan-out (pooled per-column callers pass 1)."""
        from ..native import gather_spans

        inputs = self._span_flat_inputs(field_id, include_fix=include_fix)
        if inputs is None:
            return None
        starts, lens, valid = inputs
        B = self.lines_read
        data, offsets = gather_spans(self.buf[:B], starts, lens,
                                     threads=threads)
        self._amp_normalize(field_id, data, offsets, lens, valid)
        return data, offsets, valid

    def _span_flat_inputs(self, field_id: str, include_fix: bool = False):
        """(starts, lens, valid) for a flat-gather-eligible span column;
        None when the column needs the per-row path (overrides, repair
        rows unless ``include_fix`` — the Arrow bridge gathers those raw
        and splices the repaired values in afterwards)."""
        field_id = cleanup_field_value(field_id)
        col = self._columns[field_id]
        if col["kind"] != "span" or self._overrides.get(field_id):
            return None
        B = self.lines_read
        fix = col.get("fix")
        if not include_fix and fix is not None and fix[:B].any():
            return None
        valid = (
            np.asarray(self.valid[:B]).astype(bool)
            & np.asarray(col["ok"][:B]).astype(bool)
            & ~np.asarray(col["null"][:B]).astype(bool)
        )
        starts = np.asarray(col["starts"][:B], dtype=np.int32)
        lens = np.where(
            valid, np.asarray(col["ends"][:B]) - starts, 0
        ).astype(np.int64)
        return starts, lens, valid

    def _amp_normalize(self, field_id, data, offsets, lens, valid) -> None:
        """In-place ?& query normalization on gathered bytes (offsets are
        column-local, length B+1)."""
        col = self._columns[cleanup_field_value(field_id)]
        amp = col.get("amp")
        B = self.lines_read
        if amp is not None and amp[:B].any():
            swap = valid & np.asarray(amp[:B]).astype(bool) & (lens > 0)
            at = offsets[:-1][swap]
            at = at[data[at] == np.uint8(ord("?"))]
            data[at] = np.uint8(ord("&"))

    def span_bytes_many(self, field_ids, include_fix: bool = False,
                        threads: int = 0):
        """Gather several span columns in ONE native call.

        Returns {field_id: (data_view, offsets, valid)} covering the
        subset of ``field_ids`` eligible for the flat path (same
        eligibility as :meth:`span_bytes`, except repair rows when
        ``include_fix``); ineligible columns are simply absent.  The
        threaded memcpy fan-out is paid once per batch instead of once
        per column — the difference between ~3M and ~7M rows/s through
        the Arrow bridge at 16k-row batches.  ``threads`` defaults to
        the result's assembly pool budget when one is attached."""
        from ..native import gather_spans_multi

        if not threads and self.assembly_pool is not None:
            threads = self.assembly_pool.native_threads
        B = self.lines_read
        elig = []
        for fid in field_ids:
            inputs = self._span_flat_inputs(fid, include_fix=include_fix)
            if inputs is not None:
                elig.append((cleanup_field_value(fid), inputs))
        if not elig:
            return {}
        starts = np.stack([e[1][0] for e in elig])
        lens = np.stack([e[1][1] for e in elig])
        data, goff = gather_spans_multi(self.buf[:B], starts, lens,
                                        threads=threads)
        out = {}
        for k, (fid, (_s, lens_k, valid_k)) in enumerate(elig):
            base = goff[k * B]
            offsets = goff[k * B : k * B + B + 1] - base
            col_data = data[base : int(goff[(k + 1) * B])]
            self._amp_normalize(fid, col_data, offsets, lens_k, valid_k)
            out[fid] = (col_data, offsets, valid_k)
        return out

    def to_arrow(self, include_validity: bool = True, strings: str = "view"):
        """Materialize as a pyarrow.Table (see tpu/arrow_bridge.py).

        ``strings="view"`` (default): span columns are Arrow string_view
        arrays referencing this batch's byte buffer zero-copy (the table
        keeps the buffer alive; no value bytes are copied for clean
        rows).  ``strings="copy"``: classic contiguous StringArrays."""
        from .arrow_bridge import batch_to_arrow

        return batch_to_arrow(
            self, include_validity=include_validity, strings=strings
        )

    # Column-dict entries that are NOT per-row arrays (shared metadata /
    # vocab tables) and therefore must never be row-sliced.  Explicit
    # allowlist: a geo vocab array's length could coincide with the batch
    # size, so "slice every ndarray of length B" would silently corrupt.
    _NON_ROW_KEYS = frozenset(
        ("kind", "fix_mode", "mixed_fill", "typed_kind", "dict_values")
    )

    def slice(self, start: int, stop: int) -> "BatchResult":
        """Row-window VIEW ``[start, stop)`` of this result, without
        re-materializing anything: column arrays and the byte buffer are
        numpy views, override dicts rebase to window-local row ids, and
        wildcard CSR chunks re-pack to the window's segments.

        Delivery parity contract (locked by tests/test_tpu_batch.py and
        the service's cross-session suite): every delivery surface of the
        slice — ``to_arrow``/``to_pylist``/``span_bytes``/validity/
        ``oracle_rows``/``bad_lines`` — is byte-identical to parsing the
        window's lines ALONE, because every per-line verdict (automaton
        winner, oracle routing, overrides) is computed independently per
        row.  This is what lets the serving tier's continuous batching
        coalesce many sessions into one device batch and scatter each
        session its exact solo answer (docs/SERVICE.md).

        Two deliberate non-goals: device-emitted Arrow view rows are
        DROPPED (slices deliver copy-mode Arrow — the coalesced wire
        path never ships views; ``strings="view"`` still works through
        the host gather), and the parent's batch-level rescue
        composition stats (``rescue_reasons``/``rescue_wall_s``/
        ``escaped_quote_rows``) stay on the parent — they describe the
        shared batch, not any window."""
        B = self.lines_read
        start = max(0, min(int(start), B))
        stop = max(start, min(int(stop), B))
        n = stop - start
        columns: Dict[str, Dict[str, Any]] = {}
        for fid, col in self._columns.items():
            columns[fid] = {
                k: (v if k in self._NON_ROW_KEYS
                    or not isinstance(v, np.ndarray) else v[start:stop])
                for k, v in col.items()
            }
        overrides: Dict[str, Any] = {}
        for fid, ov in self._overrides.items():
            if isinstance(ov, _LazyWildcard):
                overrides[fid] = ov.sliced(start, stop)
            else:
                overrides[fid] = {
                    i - start: v for i, v in ov.items() if start <= i < stop
                }
        valid = self.valid[start:stop]
        bad = int(np.count_nonzero(~np.asarray(valid, dtype=bool)))
        out = BatchResult(
            _SliceLines(self._lines, start, n),
            self.buf[start:stop],
            self.lengths[start:stop],
            valid,
            columns,
            overrides,
            n - bad,
            bad,
            format_index=self.format_index[start:stop],
            assembly_pool=self.assembly_pool,
        )
        ids = self.oracle_row_ids
        if ids is not None:
            lo = int(np.searchsorted(ids, start, side="left"))
            hi = int(np.searchsorted(ids, stop, side="left"))
            out.oracle_row_ids = ids[lo:hi] - start
            out.oracle_rows = hi - lo
        out.reject_reasons = {
            i - start: r for i, r in self.reject_reasons.items()
            if start <= i < stop
        }
        return out


def _bucket_batch(b: int, minimum: int = 64) -> int:
    size = minimum
    while size < b:
        size *= 2
    return size


class TpuBatchParser:
    """Compiles one LogFormat + requested fields into a fused device function
    and a host-fallback parser."""

    def __init__(
        self,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
        type_remappings: Optional[Dict[str, Any]] = None,
        extra_dissectors: Optional[Sequence[Any]] = None,
        locale: Optional[str] = None,
        view_fields: Optional[Sequence[str]] = None,
        assembly_workers: Optional[int] = None,
        data_parallel: Optional[int] = None,
        device_bytes_budget: Optional[int] = None,
        execute_deadline_s: Optional[float] = None,
        fault_policy: Optional[Any] = None,
        device_chaos: Any = None,
    ):
        self.log_format = log_format
        # Device-side data parallelism (docs/JOBS.md "Pod jobs"): lay
        # the fused parse over up to ``data_parallel`` local devices via
        # a jax.sharding Mesh ('data' axis; NamedSharding in/out) — the
        # dryrun_multichip idiom on the product hot path.  None/<=1 (or
        # a single-device host) keeps the unsharded executor.  The
        # effective width is the largest power of two that fits
        # (parallel.mesh.dp_device_count), so the power-of-two batch
        # buckets always divide evenly across devices.
        self.data_parallel = data_parallel
        self._mesh = self._build_mesh(data_parallel)
        self.requested = [cleanup_field_value(f) for f in fields]
        # Demand-driven view emission: the device emits Arrow view rows
        # only for span fields the consumer will actually deliver as
        # string_view columns.  None = all requested span fields (the
        # to_arrow default delivers every one); a sequence prunes to that
        # subset; an empty sequence disables view emission entirely
        # (equivalent to parse_batch(..., emit_views=False) per call).
        self._view_demand = (
            None if view_fields is None
            else frozenset(cleanup_field_value(f) for f in view_fields)
        )
        # One parallelism knob for the whole delivery path: Arrow column
        # assembly fan-out + the native memcpy thread budget.
        self.assembly_workers = assembly_workers
        self._assembly_pool = None

        # Host oracle parser (also the metadata source).  Pinned STATELESS:
        # the batch path guarantees deterministic per-line registration
        # priority across formats, so its fallback oracle must not carry
        # the reference's active-format state between lines (see
        # HttpdLogFormatDissector.stateless).
        self.oracle = HttpdLoglineParser(
            _CollectingRecord, log_format, timestamp_format, locale=locale
        )
        self.oracle.all_dissectors[0].stateless = True
        self.oracle.apply_config(type_remappings, extra_dissectors)
        self.oracle.add_parse_target("set_value", list(self.requested))
        self.oracle.assemble_dissectors()
        # Type remappings by complete name, used by the device plan chase.
        self._remaps = {
            k: tuple(sorted(v))
            for k, v in self.oracle.type_remappings.items()
        }

        # Consumer registry for device plan resolution: every non-root
        # dissector, keyed by input type, deduped per class in registration
        # order (mirroring the engine's one-instance-per-class-per-node rule
        # in Parser._find_useful_dissectors).  _resolve chases token outputs
        # through this registry, so EVERY producer of a requested field is
        # counted — fields with more than one producer in the oracle graph
        # (e.g. $time_local + $msec both feeding TIME.EPOCH:...epoch) must
        # resolve to "host": the oracle delivers every value in graph order
        # and the record keeps the last, which a single device route would
        # silently break.
        fmt_root = self.oracle.all_dissectors[0]
        self._consumers: Dict[str, List[Any]] = {}
        seen_consumer = set()
        for d in self.oracle.all_dissectors:
            if d is fmt_root:
                continue
            # No try/except around get_possible_output(): a raising
            # dissector would silently drop producer edges, letting a
            # device plan claim a multi-producer field — fail loudly.
            d.get_possible_output()
            key = (d.get_input_type(), type(d))
            if key in seen_consumer:
                continue
            seen_consumer.add(key)
            self._consumers.setdefault(d.get_input_type(), []).append(d)

        # Device programs: one FormatUnit per registered format, in
        # registration order (SURVEY §7.7 "run k format automata, pick the
        # per-line winner").  An UNCOMPILABLE format does not truncate the
        # list: it contributes a plausibility-only probe unit
        # (separator-order automaton, valid bit never set) at its ordinal,
        # so (a) later compilable formats still run on device, and (b) a
        # line is never claimed by format k while the uncompilable format
        # j < k is still plausible — those lines go to the oracle, which
        # applies the reference's registration-priority semantics
        # (HttpdLogFormatDissector.java:174-204) with the real regexes.
        fmt = self.oracle.all_dissectors[0]
        dissectors = getattr(fmt, "dissectors", [fmt])
        from .pipeline import CSR_SLOTS
        from .program import compile_plausibility_program

        self.csr_slots = CSR_SLOTS
        self.units: List[FormatUnit] = []
        for d in dissectors:
            try:
                prog = compile_device_program(d)
            except UnsupportedFormatError:
                self.units.append(FormatUnit(
                    compile_plausibility_program(d), [],
                    PackedLayout.for_plans([], self.csr_slots),
                    plausibility_only=True,
                ))
                continue
            plans = [self._resolve(prog, fid) for fid in self.requested]
            self.units.append(FormatUnit(
                prog, plans, PackedLayout.for_plans(plans, self.csr_slots)
            ))
        assign_row_offsets(self.units)
        # The definitely-bad filter (implausible for every format -> no
        # oracle visit) is sound because EVERY registered format has a
        # device automaton — full or plausibility-only probe.  Always True
        # for freshly-built parsers; kept as state (not an invariant)
        # because LOADED artifacts from pre-probe builds carry truncated
        # unit lists with the flag False (__setstate__).
        self._device_covers_all_formats = len(self.units) == len(dissectors)

        # Merged per-field plan: the first non-host kind across formats (used
        # for numeric coercion of oracle-delivered values).
        self.plan_by_id = {
            fid: self._merged_plan(fid) for fid in self.requested
        }
        # Fields that need the oracle for EVERY line (host under all formats).
        self.host_fields = [
            fid for fid, p in self.plan_by_id.items() if p.kind == "host"
        ]
        # Casts for EVERY requested field: any field can take the host path
        # on some line (host-only fields always; device fields when the
        # line's winning format resolves them as host — e.g. multi-producer
        # fields like `%B ... %b` — or when the line goes to the oracle).
        self._host_casts = {
            fid: self.oracle.get_casts(fid) for fid in self.requested
        }
        # Setter-cast dispatch flags (LONG, DOUBLE) per field: the single
        # source for both _coerce_casts and the oracle delivery plan.
        self._cast_flags = {
            f: (Cast.LONG in c, Cast.DOUBLE in c)
            for f, c in self._host_casts.items()
            if c is not None
        }
        self._overflow_delivery = self._build_overflow_delivery()
        # Per-unit: fields the oracle must supply for lines won by that unit
        # (host under it, or a kind-group mismatch with the merged column).
        self._unit_oracle_fields: List[List[str]] = [
            [
                fid
                for fid in self.requested
                if not self._unit_decodable(u, fid)
            ]
            for u in self.units
        ]
        # Device fault layer (docs/FAULTS.md): pre-allocation byte
        # budget, OOM bisect + bucket clamp, execution deadline on an
        # abandonable worker, per-parser circuit breaker demoting a
        # repeatedly-faulting kernel to the host oracle, and the chaos
        # injection hooks that drill all of it.
        self._init_fault_layer(
            device_bytes_budget, execute_deadline_s, fault_policy,
            device_chaos,
        )
        self._jitted = self._build_jitted()
        self._jitted_views = None  # lazily built by device_views_fn()
        # Aggregate-pushdown executors (docs/ANALYTICS.md): canonical
        # spec key -> (csr_slots at build, jitted reduction, op plans).
        # _agg_disabled holds spec keys whose reduction failed to
        # COMPILE — permanently demoted to the exact row-path fallback.
        self._agg_fns: Dict[str, tuple] = {}
        self._agg_disabled: set = set()

    def _init_fault_layer(self, budget, deadline, policy, chaos) -> None:
        """Device-tier fault state — shared by ``__init__`` and
        ``__setstate__``: artifacts never carry runtime fault state
        (breakers, clamps, chaos) — it re-arms on the loading host from
        the pickled knobs + the env fallbacks."""
        from .device_faults import (
            DeviceBreaker,
            DeviceFaultPolicy,
            resolve_budget,
            resolve_deadline,
        )

        self.fault_policy = policy or DeviceFaultPolicy()
        self.device_bytes_budget = resolve_budget(budget)
        self.execute_deadline_s = resolve_deadline(deadline)
        self._breaker = DeviceBreaker(
            self.fault_policy.breaker_threshold,
            self.fault_policy.breaker_cooloff_s,
        )
        self._oom_clamp: Optional[int] = None
        self._oom_events = 0
        self._device_chaos = None
        self.arm_device_chaos(chaos if chaos is not None else "env")

    def arm_device_chaos(self, chaos: Any) -> None:
        """Arm (or disarm with ``None``) device-tier fault injection:
        accepts a ``tools.chaos.DeviceChaos``, a ``ChaosSpec``, the
        grammar string, or ``"env"`` (the ``LOGPARSER_TPU_CHAOS``
        channel — also the construction-time default, so CLI drills arm
        the whole stack with one env var).  A spec carrying no device
        faults leaves the hot path untouched (no hook object at all)."""
        if chaos is None:
            self._device_chaos = None
            return
        from ..tools.chaos import ChaosSpec, DeviceChaos

        if isinstance(chaos, DeviceChaos):
            self._device_chaos = chaos or None
            return
        if chaos == "env":
            spec = ChaosSpec.from_env()
        elif isinstance(chaos, str):
            spec = ChaosSpec.parse(chaos)
        else:
            spec = chaos
        dc = DeviceChaos(spec) if spec is not None else None
        self._device_chaos = dc or None

    def device_fault_stats(self) -> Dict[str, Any]:
        """Fault-layer introspection for drills/ops: breaker state, the
        standing OOM clamp, and whether chaos is armed."""
        return {
            **self._breaker.stats(),
            "oom_clamp": self._oom_clamp,
            "oom_events": self._oom_events,
            "chaos_armed": self._device_chaos is not None,
        }

    @staticmethod
    def _build_mesh(data_parallel: Optional[int]):
        """The 'data'-axis mesh a data_parallel request resolves to on
        THIS host, or None for the unsharded executor (no request, one
        device, or a 1-wide resolution)."""
        if not data_parallel or int(data_parallel) <= 1:
            return None
        from ..observability import metrics
        from ..parallel.mesh import dp_device_count, make_mesh

        n = dp_device_count(int(data_parallel))
        if n <= 1:
            return None
        metrics().gauge_set("device_mesh_devices", n)
        return make_mesh(n_data=n)

    @property
    def mesh_devices(self) -> int:
        """How many devices the executor is laid out over (1 = no mesh)."""
        return self._mesh.devices.size if self._mesh is not None else 1

    def _bucket(self, b: int) -> int:
        """The padded batch size of a ``b``-line batch: the power-of-two
        bucket, floored at the mesh width so a sharded batch axis always
        divides evenly across devices."""
        size = _bucket_batch(b)
        if self._mesh is not None:
            size = max(size, self._mesh.devices.size)
        return size

    def _build_jitted(self):
        # No point running the device programs when every field is host-only.
        any_device_field = any(
            p.kind != "host" for u in self.units for p in u.plans
        )
        if self.units and any_device_field:
            return self._aot_wrap(
                build_units_jnp_fn(self.units, mesh=self._mesh), "plain"
            )
        return None

    def _aot_wrap(self, jit_fn, tag: str, specs=None):
        """Wrap a fresh jit executor in the AOT compile-cache layer (see
        tpu/compile_cache.py + docs/COMPILE.md).  Mesh-sharded executors
        stay in-memory only: their serialized form binds this process's
        device set."""
        from .compile_cache import AotExecutor

        return AotExecutor(
            jit_fn,
            self.executor_fingerprint(tag, specs),
            serializable=self._mesh is None,
        )

    def executor_fingerprint(self, tag: str, specs=None) -> str:
        """Content hash of everything that shapes the compiled executor:
        the device programs + field plans (which fold in format strings,
        requested fields, remappings, extra dissectors, geo tables), the
        CSR slot count (adaptive growth = new fingerprint), the mesh
        width, the executor variant (plain/views + its specs), and the
        pipeline code version.  Any drift is a cache MISS — a stale
        kernel can never load."""
        from .compile_cache import code_fingerprint, stable_hash

        return stable_hash((
            code_fingerprint(),
            tag,
            list(specs) if specs else [],
            self.csr_slots,
            self.mesh_devices,
            [
                (u.plausibility_only, u.row_offset, u.program, u.plans)
                for u in self.units
            ],
        ))

    def assembly_pool(self):
        """The shared delivery-path worker pool (lazily built; see
        tpu/hostpool.py).  BatchResults carry a reference so to_arrow
        inherits the knob wherever the result travels."""
        if self._assembly_pool is None:
            from .hostpool import AssemblyPool

            self._assembly_pool = AssemblyPool(self.assembly_workers)
        return self._assembly_pool

    def _view_specs(self):
        """Static spec for device-side Arrow view emission: span-group
        fields + the units the host would decode each from (the
        ``_unit_decodable`` rule — other units' lines deliver via oracle
        overrides, whose views the host patches anyway).  Pruned to the
        demand set when the parser was built with ``view_fields``."""
        specs = []
        for fid in self.requested:
            if fid.endswith(".*"):
                continue
            if self._view_demand is not None and fid not in self._view_demand:
                continue
            if self._plan_group(self.plan_by_id[fid]) != "span":
                continue
            unit_idx = [
                ui for ui, u in enumerate(self.units)
                if not u.plausibility_only and self._unit_decodable(u, fid)
            ]
            if unit_idx:
                specs.append((fid, tuple(unit_idx)))
        return specs

    def device_views_fn(self):
        """The executor variant that also emits Arrow view rows (4 int32
        rows per span field, appended after the unit rows) — the
        parse_batch product path.  Falls back to the plain executor when
        no span field is device-decodable."""
        if self._jitted is None:
            return None
        if self._jitted_views is None:
            specs = self._view_specs()
            if not specs:
                self._jitted_views = self._jitted
                self._views_fields = []
            else:
                self._jitted_views = self._aot_wrap(
                    build_units_jnp_fn(self.units, specs, mesh=self._mesh),
                    "views", specs,
                )
                self._views_fields = [fid for fid, _ in specs]
        return self._jitted_views

    def device_fn(self):
        """The fused plain-XLA device executor, or None when every field
        is host-only (shape-polymorphic jit; each [B, L] bucket compiles
        once).  XLA is the product path: a hand-written Pallas kernel of
        this pipeline measured ~4.5x slower on v5e and Mosaic cannot
        lower the chained stages — see the ADR in COMPONENTS.md."""
        return self._jitted

    def prewarm(
        self,
        batch_sizes: Optional[Sequence[int]] = None,
        max_line_len: int = 256,
        emit_views: Optional[bool] = None,
    ) -> Dict[str, str]:
        """Make the shape-bucket ladder executable OFF the request path:
        for each batch size, resolve the (padded-B, L-bucket) executable —
        in-memory map, then the persistent compile cache
        (``LOGPARSER_TPU_COMPILE_CACHE``), then an explicit lower+compile
        written back to the cache.  ``max_line_len`` picks the line-length
        bucket to warm (the same ``runtime.bucket_length`` the encoder
        applies).  Returns ``{"BxL": "memory"|"disk"|"compiled"}`` per
        warmed shape; a no-device-field parser returns ``{}``.

        Sidecar boot and front-tier respawn warmup call this from a
        background thread (docs/SERVICE.md): a cache-warm fleet boots
        with zero compiles on the serving path."""
        from .compile_cache import DEFAULT_BUCKET_LADDER
        from .runtime import bucket_length

        executors = []
        if emit_views is None or emit_views:
            fn = self.device_views_fn()
            if fn is not None:
                executors.append(fn)
        if emit_views is None or not emit_views:
            fn = self.device_fn()
            if fn is not None and fn not in executors:
                executors.append(fn)
        if not executors:
            return {}
        line_len = bucket_length(max(1, max_line_len))
        out: Dict[str, str] = {}
        for b in batch_sizes or DEFAULT_BUCKET_LADDER:
            padded = self._bucket(int(b))
            for fn in executors:
                src = fn.warm(padded, line_len)
                shape = f"{padded}x{line_len}"
                # Report the coldest source across the executor variants.
                rank = {"memory": 0, "disk": 1, "compiled": 2}
                if rank[src] >= rank.get(out.get(shape, "memory"), 0):
                    out[shape] = src
        return out

    def _grow_csr_slots(self) -> bool:
        """Adaptive CSR: double the wildcard segment-slot count (bounded by
        CSR_SLOTS_MAX) and rebuild the packed layouts + executor.  Called
        when a batch flags CSR overflow, so query-heavy corpora cost a few
        recompiles instead of routing every long line to the per-line
        oracle.  Returns False at the cap (those lines stay oracle-bound)."""
        from .pipeline import CSR_SLOTS_MAX

        if self.csr_slots >= CSR_SLOTS_MAX:
            return False
        self.csr_slots *= 2
        for u in self.units:
            u.layout = PackedLayout.for_plans(u.plans, self.csr_slots)
        assign_row_offsets(self.units)
        self._jitted = self._build_jitted()
        self._jitted_views = None  # row offsets moved; rebuild lazily
        return True

    # ------------------------------------------------------------------

    def _merged_plan(self, field_id: str) -> _FieldPlan:
        for u in self.units:
            p = u.plan_for(field_id)
            if p.kind != "host":
                return p
        return _FieldPlan(field_id, "host")

    @staticmethod
    def _plan_group(plan: _FieldPlan) -> str:
        """Merge group: plans in the same group share column arrays."""
        if plan.kind == "span":
            return "span"
        if plan.kind in ("long", "secmillis"):
            return "numeric"
        if plan.kind == "ts":
            return "numeric" if timefields.is_numeric_output(plan.comp) else "obj"
        if plan.kind == "muid":
            return "obj" if plan.comp == "ip" else "numeric"
        if plan.kind == "ulist":
            return "span"
        if plan.kind == "qscsr":
            return "wild"
        if plan.kind == "geo":
            return "obj"
        return "host"

    def _unit_decodable(self, unit: FormatUnit, field_id: str) -> bool:
        """Can lines won by `unit` take this field from the device output?"""
        merged = self.plan_by_id[field_id]
        if merged.kind == "host":
            return False
        return self._plan_group(unit.plan_for(field_id)) == self._plan_group(
            merged
        )

    # -- device plan resolution ----------------------------------------

    def _resolve(self, program: DeviceProgram, field_id: str) -> _FieldPlan:
        """Map one requested field to its device plan by chasing every
        token output through the consumer registry (the device-compiler
        mirror of Parser._find_useful_dissectors).

        A field is device-resolvable only when EXACTLY ONE chase path
        produces it and every step of that path is device-modeled.  With
        multiple producers (e.g. `%B ... %b`: the direct BYTESCLF token
        plus the ConvertNumberIntoCLF edge from the BYTES token both feed
        BYTESCLF:response.body.bytes) the oracle delivers every value in
        graph order and the record keeps the last; a single-path device
        plan would silently pick one — so such fields go to the oracle."""
        ftype, _, path = field_id.partition(":")
        candidates: List[_FieldPlan] = []
        for tok in program.tokens:
            for out_type, out_name in tok.outputs:
                candidates.extend(
                    self._chase(
                        field_id, ftype, path, tok, out_type, out_name,
                        vctx=("", "", 1), steps=(), device_ok=True,
                        depth=6, visited=frozenset(),
                    )
                )
        if len(candidates) == 1 and candidates[0].kind != "host":
            return candidates[0]
        return _FieldPlan(field_id, "host")

    def _terminal_plan(
        self, field_id: str, tok, vctx, steps, device_ok
    ) -> _FieldPlan:
        """Build the plan for a chase path that reached the requested field.
        vctx = (parse, null_mode, scale) accumulated value conversions."""
        if not device_ok:
            return _FieldPlan(field_id, "host")
        parse, null_mode, scale = vctx
        if parse == "":
            # No value conversion: a raw (sub-)span.  Direct token captures
            # with a numeric charset deliver typed int64 (the reference
            # types them via Casts at the setter).
            if steps:
                return _FieldPlan(field_id, "span", tok.index, steps)
            # NARROW charsets under-approximate the regex (list tokens):
            # the host types those by casts (STRING), not by charset.
            if tok.charset == CS_DIGITS and not tok.narrow:
                return _FieldPlan(field_id, "long", tok.index)
            if tok.charset == CS_CLF_DIGITS and not tok.narrow:
                return _FieldPlan(
                    field_id, "long", tok.index, null_mode="dash_null"
                )
            return _FieldPlan(field_id, "span", tok.index)
        return _FieldPlan(
            field_id, parse, tok.index, steps, null_mode=null_mode, scale=scale
        )

    def _step_spec(self, d, oname: str, vctx, steps, device_ok):
        """How consumer dissector `d` transforms a chase path for output
        `oname`.  Returns (kind, new_vctx, new_steps, new_device_ok, comp,
        meta) where kind is "value" (value-level), "span" (span transform)
        or "ts" (terminal timestamp component)."""
        from ..dissectors.firstline import (
            HttpFirstLineDissector,
            HttpFirstLineProtocolDissector,
        )
        from ..dissectors.strftime_stamp import StrfTimeStampDissector
        from ..dissectors.timestamp import TimeStampDissector
        from ..dissectors.uri import HttpUriDissector
        from ..dissectors.translate import (
            ConvertCLFIntoNumber,
            ConvertMillisecondsIntoMicroseconds,
            ConvertNumberIntoCLF,
            ConvertSecondsWithMillisStringDissector,
        )
        from .timeparse import compile_layout_for_device

        parse, null_mode, scale = vctx
        if isinstance(d, ConvertCLFIntoNumber) and parse == "":
            return ("value", ("long", "dash_zero", scale), steps, device_ok)
        if isinstance(d, ConvertNumberIntoCLF) and parse == "":
            return ("value", ("long", "zero_null", scale), steps, device_ok)
        if isinstance(d, ConvertSecondsWithMillisStringDissector) and parse == "":
            return ("value", ("secmillis", "", scale), steps, device_ok)
        if isinstance(d, ConvertMillisecondsIntoMicroseconds):
            new_parse = parse or "long"
            return ("value", (new_parse, null_mode, scale * 1000), steps, device_ok)
        if isinstance(d, HttpFirstLineDissector) and parse == "":
            part = {"method": "method", "uri": "uri", "protocol": "protocol"}.get(
                oname
            )
            if part is not None:
                return ("span", vctx, steps + (("fl", part),), device_ok)
        if isinstance(d, HttpFirstLineProtocolDissector) and parse == "":
            # "HTTP/1.1" -> protocol ("" output name: keeps the input path)
            # + version.  A span split at the first '/', device-exact.
            if oname in ("", "version"):
                return (
                    "span", vctx,
                    steps + (("pv", "version" if oname else "protocol"),),
                    device_ok,
                )
        if isinstance(d, HttpUriDissector) and parse == "":
            if oname == "port":
                # Port is numeric on the host (uri.port int, STRING_OR_LONG
                # casts): terminal long parse over the device port span.
                return (
                    "value", ("long", null_mode, scale),
                    steps + (("uri", oname),), device_ok,
                )
            if oname in (
                "protocol", "userinfo", "host", "path", "query", "ref"
            ):
                return ("span", vctx, steps + (("uri", oname),), device_ok)
        from ..httpd.nginx_modules.upstream import UpstreamListDissector

        if isinstance(d, UpstreamListDissector) and parse == "":
            # Indexed upstream-list elements: device-eligible when the
            # output is STRING_ONLY (numeric-casted lists deliver typed
            # values through the oracle's casts dispatch).
            from ..core.casts import STRING_ONLY as _SO

            u_idx, _, u_which = oname.partition(".")
            if u_which in ("value", "redirected") and u_idx.isdigit():
                casts = (
                    d.output_original_casts if u_which == "value"
                    else d.output_redirected_casts
                )
                return (
                    "ulist", vctx, steps,
                    device_ok and casts == _SO,
                    oname, (int(u_idx), u_which),
                )
        from ..dissectors.mod_unique_id import ModUniqueIdDissector

        if isinstance(d, ModUniqueIdDissector) and parse == "":
            if oname in ("epoch", "ip", "processid", "counter", "threadindex"):
                return ("muid", vctx, steps, device_ok, oname, None)
        from ..geoip.dissectors import AbstractGeoIPDissector

        if isinstance(d, AbstractGeoIPDissector) and parse == "":
            table = self._geo_table_for(d) if device_ok else None
            if table is not None and oname in table.columns:
                tag = f"{type(d).__name__}:{d.database_file_name}"
                return ("geo", vctx, steps, device_ok, oname,
                        (tag, oname, table))
            return ("geo", vctx, steps, False, oname, None)
        if isinstance(d, (TimeStampDissector, StrfTimeStampDissector)) and parse == "":
            if oname in timefields.DEVICE_COMPONENTS:
                inner = (
                    d.timestamp_dissector
                    if isinstance(d, StrfTimeStampDissector)
                    else d
                )
                try:
                    dl = compile_layout_for_device(inner.get_layout())
                except ValueError:
                    dl = None  # pattern the layout compiler rejects: host
                if dl is not None:
                    return ("ts", vctx, steps, device_ok, oname, dl)
            return ("ts", vctx, steps, False, oname, None)
        # Not device-modeled: the path still counts as a producer.
        return ("value", vctx, steps, False)

    def _geo_table_for(self, d):
        """Build (once per database) the flattened device range-join table
        for a GeoIP dissector; None when the database cannot back one."""
        from ..geoip.device import _EXTRACTORS, GeoDeviceTable
        from ..geoip.mmdb import MMDBReader

        key = (type(d).__name__, d.database_file_name)
        if not hasattr(self, "_geo_tables"):
            self._geo_tables: Dict[tuple, Any] = {}
        if key not in self._geo_tables:
            try:
                columns = [
                    o.partition(":")[2]
                    for o in d.get_possible_output()
                    if o.partition(":")[2] in _EXTRACTORS
                ]
                reader = MMDBReader(d.database_file_name)
                self._geo_tables[key] = GeoDeviceTable(reader, columns)
            except Exception:
                self._geo_tables[key] = None
        return self._geo_tables[key]

    def _chase(
        self, field_id, ftype, path, tok, t, name,
        vctx, steps, device_ok, depth, visited, remapped=False,
    ) -> List[_FieldPlan]:
        """All ways field (t:name) — reachable from `tok` via `steps` and
        `vctx` — leads to the requested (ftype:path).  Device plans where
        every step is modeled; "host" placeholders otherwise (they count
        toward the multi-producer guard)."""
        plans: List[_FieldPlan] = []
        if t == ftype and name == path:
            plans.append(self._terminal_plan(field_id, tok, vctx, steps, device_ok))
            return plans
        if (t, name) in visited:
            return plans  # cycle: its producer paths are already counted
        relevant = name == "" or path == name or path.startswith(name + ".")
        if not relevant:
            return plans
        if depth == 0:
            # Fail SAFE on depth exhaustion: a truncated path may still be
            # a real producer — count it as host so the multi-producer
            # guard cannot be starved by deep chains.
            plans.append(_FieldPlan(field_id, "host"))
            return plans
        visited = visited | {(t, name)}
        # Type remappings re-type this name: the engine re-delivers the
        # same value under each mapped type (Parsable's remap recursion,
        # NOT nested — hence the `remapped` flag), so every consumer of a
        # mapped type is a producer path too, and the mapped field itself
        # is deliverable as a raw span (remapped targets are STRING_ONLY).
        if not remapped:
            for ntype in self._remaps.get(name, ()):
                if ntype == t:
                    continue
                plans.extend(self._chase(
                    field_id, ftype, path, tok, ntype, name,
                    vctx, steps, device_ok, depth - 1, visited,
                    remapped=True,
                ))
        for d in self._consumers.get(t, ()):
            for out in d.get_possible_output():
                ot, _, oname = out.partition(":")
                if oname == "*":
                    # Wildcard outputs (query-string/cookies): any requested
                    # path under this prefix is produced here.
                    if ot == ftype and path.startswith(name + "."):
                        from ..dissectors.cookies import (
                            RequestCookieListDissector,
                            ResponseSetCookieListDissector,
                        )
                        from ..dissectors.query import QueryStringFieldDissector

                        mode = None
                        if isinstance(d, QueryStringFieldDissector):
                            mode = "query"
                        elif isinstance(d, RequestCookieListDissector):
                            mode = "cookie"
                        elif isinstance(d, ResponseSetCookieListDissector):
                            mode = "setcookie"
                        if mode is not None and vctx[0] == "" and device_ok:
                            plans.append(_FieldPlan(
                                field_id, "qscsr", tok.index, steps,
                                comp=path[len(name) + 1:], meta=mode,
                            ))
                        else:
                            plans.append(_FieldPlan(field_id, "host"))
                    elif path.startswith(name + "."):
                        # Per-cookie ATTRIBUTE through the Set-Cookie
                        # wildcard: response.cookies.<cookie>.<attr> with
                        # the attr typed by ResponseSetCookieDissector
                        # (STRING value/path/domain/comment/expires;
                        # TIME.EPOCH expires).  The cookie name is
                        # everything before the last component (names may
                        # contain dots).
                        from ..dissectors.cookies import (
                            ResponseSetCookieListDissector,
                        )

                        rest = path[len(name) + 1:]
                        cname, _, attr = rest.rpartition(".")
                        typed = (
                            ftype == "STRING"
                            and attr in ("value", "path", "domain",
                                         "comment", "expires")
                        ) or (ftype == "TIME.EPOCH" and attr == "expires")
                        if (
                            isinstance(d, ResponseSetCookieListDissector)
                            and cname and typed
                        ):
                            if vctx[0] == "" and device_ok:
                                plans.append(_FieldPlan(
                                    field_id, "qscsr", tok.index, steps,
                                    comp=cname, meta="setcookie", attr=attr,
                                ))
                            else:
                                plans.append(_FieldPlan(field_id, "host"))
                    # A wildcard param REMAPPED to another type (the
                    # reference's query.res -> SCREENRESOLUTION demo): the
                    # engine re-types the delivered param value, so the
                    # remapped type's consumers become producers too.
                    if not remapped:
                        plans.extend(self._chase_wildcard_remaps(
                            field_id, ftype, path, tok, d, name,
                            vctx, steps, device_ok,
                        ))
                    continue
                if oname == "":
                    new_name = name
                else:
                    new_name = name + "." + oname if name else oname
                if not (path == new_name or path.startswith(new_name + ".")):
                    continue
                spec = self._step_spec(d, oname, vctx, steps, device_ok)
                kind = spec[0]
                if kind in ("ts", "geo", "muid", "ulist"):
                    _, nctx, nsteps, ndev, comp, meta = spec
                    if path == new_name and ot == ftype:
                        if ndev:
                            plans.append(_FieldPlan(
                                field_id, kind, tok.index, nsteps,
                                comp=comp, meta=meta,
                            ))
                        else:
                            plans.append(_FieldPlan(field_id, "host"))
                    # ts/geo outputs are terminal values; nothing deeper.
                    continue
                _, nctx, nsteps, ndev = spec
                if path == new_name and ot == ftype:
                    plans.append(
                        self._terminal_plan(field_id, tok, nctx, nsteps, ndev)
                    )
                else:
                    plans.extend(self._chase(
                        field_id, ftype, path, tok, ot, new_name,
                        nctx, nsteps, ndev, depth - 1, visited,
                    ))
        return plans

    def _chase_wildcard_remaps(
        self, field_id, ftype, path, tok, d, name, vctx, steps, device_ok,
    ) -> List[_FieldPlan]:
        """Producer paths through a remapped wildcard param.

        The wildcard delivers ``STRING:name.<param>``; a type remapping on
        that complete name re-delivers the value under the mapped type,
        whose consumers then sub-dissect it.  Device-modeled today: the
        remapped raw value itself (the CSR segment value span) and
        ScreenResolutionDissector's width/height (host-side split of the
        matched segment, like set-cookie attrs).  Anything else counts as
        a host producer."""
        from ..dissectors.cookies import RequestCookieListDissector
        from ..dissectors.query import QueryStringFieldDissector
        from ..dissectors.screenres import ScreenResolutionDissector

        mode = None
        if isinstance(d, QueryStringFieldDissector):
            mode = "query"
        elif isinstance(d, RequestCookieListDissector):
            mode = "cookie"
        plans: List[_FieldPlan] = []
        prefix = name + "."
        for remap_key, ntypes in self._remaps.items():
            if not remap_key.startswith(prefix):
                continue
            param = remap_key[len(prefix):]
            if path == remap_key:
                # The remapped raw value itself under one of its new types.
                for ntype in ntypes:
                    if ftype == ntype:
                        if mode is not None and vctx[0] == "" and device_ok:
                            plans.append(_FieldPlan(
                                field_id, "qscsr", tok.index, steps,
                                comp=param, meta=mode,
                            ))
                        else:
                            plans.append(_FieldPlan(field_id, "host"))
                continue
            if not path.startswith(remap_key + "."):
                continue
            sub = path[len(remap_key) + 1:]
            for ntype in ntypes:
                for d2 in self._consumers.get(ntype, ()):
                    for out2 in d2.get_possible_output():
                        ot2, _, oname2 = out2.partition(":")
                        if oname2 == sub and ot2 == ftype:
                            if (
                                isinstance(d2, ScreenResolutionDissector)
                                and oname2 in ("width", "height")
                                and mode is not None
                                and vctx[0] == "" and device_ok
                            ):
                                plans.append(_FieldPlan(
                                    field_id, "qscsr", tok.index, steps,
                                    comp=param, meta=mode,
                                    attr=("sres", d2.separator, oname2),
                                ))
                            else:
                                plans.append(_FieldPlan(field_id, "host"))
                        elif sub.startswith(oname2 + "."):
                            # Deeper chains through the remapped type are
                            # not modeled: count the producer, go host.
                            plans.append(_FieldPlan(field_id, "host"))
        return plans

    # ------------------------------------------------------------------

    @staticmethod
    def _geo_typed_fill(col, sel, typed, miss, kind_ch):
        """Carry a numeric geo column's raw values + miss mask alongside
        the object array so the Arrow bridge can build the typed column
        without per-element inference.  Mixed numeric kinds across fills
        disable the fast path (typed_kind=None)."""
        B = len(typed)
        if "typed_values" not in col:
            col["typed_values"] = np.zeros(
                B, dtype=np.float64 if kind_ch == "f" else np.int64
            )
            col["typed_miss"] = np.ones(B, dtype=bool)
            col["typed_kind"] = kind_ch
        if col.get("typed_kind") == kind_ch:
            col["typed_values"] = np.where(sel, typed, col["typed_values"])
            col["typed_miss"] = np.where(sel, miss, col["typed_miss"])
        else:
            col["typed_kind"] = None

    def parse_batch(
        self, lines: Sequence[Union[bytes, str]],
        emit_views: Optional[bool] = None,
    ) -> BatchResult:
        """``emit_views=False`` runs the plain executor (no device Arrow
        view rows): the demand knob for consumers that never deliver
        string_view columns — copy-mode Arrow (parse_to_ipc, the sidecar
        wire) and the per-record adapter paths — so they stop paying the
        view-emission kernel cost and the larger packed D2H.  Default
        (None/True): the product path with views."""
        return self._finish_batch(
            self._dispatch_batch(self._encode_batch(lines), emit_views)
        )

    def parse_blob(
        self, data: Union[bytes, bytearray, memoryview],
        emit_views: Optional[bool] = None,
    ) -> BatchResult:
        """Newline-delimited log bytes -> BatchResult without building a
        Python line list: the native framer packs the padded [B, L]
        buffer straight from the blob, and per-line bytes materialize
        lazily — only for oracle-rescued rows.  The product ingest path
        (the sidecar's LINES payload and file readers are exactly this
        shape; reference analogue: the Hadoop text input path hands raw
        line Writables to the parser,
        ApacheHttpdLogfileInputFormat.java:1).

        Framing semantics are encode_blob's: a final empty segment after
        a trailing newline is dropped, and one trailing ``\\r`` per line
        is stripped — callers needing exact list semantics for such
        inputs use :meth:`parse_batch`."""
        from ..native import encode_blob
        from ..observability import pipeline_stage, record_batch_shape

        data = bytes(data)
        lines = _BlobLines(data)
        B = len(lines)
        with pipeline_stage("encode", items=B):
            buf, lengths, overflow = encode_blob(data)
        if buf.shape[0] != B:  # framer/view disagreement: authoritative path
            return self.parse_batch(list(lines), emit_views=emit_views)
        padded_b = self._bucket(B)
        if padded_b != B:
            buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
            lengths = np.pad(lengths, (0, padded_b - B))
        record_batch_shape(B, padded_b, buf.shape[1], int(lengths.sum()))
        enc = (lines, buf, lengths, overflow, B, padded_b)
        return self._finish_batch(self._dispatch_batch(enc, emit_views))

    def parse_encoded(
        self, batch, emit_views: Optional[bool] = None,
    ) -> BatchResult:
        """One feeder-framed batch (:class:`logparser_tpu.feeder.worker.
        EncodedBatch`) -> BatchResult, without re-scanning the payload:
        the feeder worker already ran the ``parse_blob`` framing
        (``encode_blob``) in its own process, so this path only pads the
        batch dimension to its bucket and dispatches.  Framing semantics
        and results are byte-identical to :meth:`parse_blob` over the
        same bytes — the feeder parity suite pins it."""
        return self._finish_batch(
            self._dispatch_batch(self._adopt_encoded(batch), emit_views)
        )

    def _adopt_encoded(self, batch):
        """EncodedBatch -> the in-flight enc tuple ``_dispatch_batch``
        consumes.  Lines stay lazy (``_BlobLines`` over the shipped
        payload — only oracle-rescued rows ever materialize).  A
        framer/count disagreement falls back to the authoritative
        per-line path, mirroring :meth:`parse_blob`.

        Ring batches (shared-memory slot views, feeder ring transport):
        the PAYLOAD stays a zero-copy slot view end to end — rescue rows
        read it in place during materialization, after which the stream
        releases the slot.  The frame arrays are adopted into owned
        buffers (the bucket pad does it for free on partial batches; an
        exact-bucket batch pays one memcpy) because ``BatchResult.buf``
        backs host span gathers and string_view tables for as long as
        the caller keeps the result — longer than a recycling slot may
        live."""
        from ..observability import pipeline_stage, record_batch_shape

        payload = batch.payload
        if not isinstance(payload, (bytes, bytearray, np.ndarray)):
            payload = bytes(payload)
        lines = _BlobLines(payload)
        B = len(lines)
        buf, lengths = batch.buf, batch.lengths
        if B != batch.n_lines or buf.shape[0] != B:
            return self._encode_batch(list(lines))
        leased = getattr(batch, "ring", None) is not None
        with pipeline_stage("encode", items=0):
            # Adoption cost only (row padding / lease copy): the real
            # encode ran in the feeder worker under feeder_encode.
            padded_b = self._bucket(B)
            if padded_b != B:
                buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
                lengths = np.pad(lengths, (0, padded_b - B))
            elif leased:
                buf = np.array(buf, copy=True)
                lengths = np.array(lengths, copy=True)
        record_batch_shape(B, padded_b, buf.shape[1], int(lengths.sum()))
        return (lines, buf, lengths, list(batch.overflow), B, padded_b)

    def parse_batch_stream(
        self,
        batches,
        depth: int = 1,
        emit_views: Optional[bool] = None,
        stage_h2d: Optional[bool] = None,
    ):
        """Batches-in-flight streaming: yields one BatchResult per input
        batch, in order, overlapping the host-side stages with device
        work.  JAX dispatch is async, so per iteration the ENCODE of
        batch k+1 runs while batch k computes on device, and the
        MATERIALIZATION of batch k runs while batch k+1 computes.
        Counters stay exact: every result is materialized by the same
        code path as :meth:`parse_batch`.

        ``depth`` is the number of batches whose device work may be in
        flight simultaneously.  The default of 1 keeps the device link
        in natural order (H2D k, D2H k, H2D k+1, ...) — measured on
        tunneled/half-duplex attachments, queueing the NEXT batch's
        upload ahead of the current download makes the stream SLOWER
        than serialized parse_batch, so deeper queues only pay on
        full-duplex (PCIe) attachments where transfers overlap.

        Adaptive-CSR interplay: growing the slot count rebuilds the
        executor, which invalidates in-flight dispatches — each pending
        batch snapshots the slot count at dispatch and transparently
        re-dispatches on mismatch (bounded, slots only ever double).

        Items may also be feeder-framed batches
        (:class:`logparser_tpu.feeder.worker.EncodedBatch`, e.g. from
        ``FeederPool.batches()``): those skip the host encode entirely —
        the framing already happened in the feeder worker.  Ring batches
        (``FeederPool.batches(detach=False)`` / ``feed()``) are RELEASED
        by the stream once their result materializes — device upload
        done, rescue payload consumed — so the zero-copy slots recycle
        exactly one materialization behind delivery.

        ``stage_h2d`` double-buffers the host->device edge: batch k+1's
        encoded frame is handed to ``jax.device_put`` BEFORE the stream
        blocks on batch k's D2H fetch, so the upload overlaps the
        in-flight device work instead of queueing behind the fetch (the
        gap ``observe_stage`` used to charge to ``encode``/``device``).
        Default (None): enabled unless ``LOGPARSER_TPU_STAGED_H2D=0`` —
        the opt-out exists because staging reorders the link to
        H2D(k+1)-before-D2H(k), which can HURT on tunneled/half-duplex
        attachments for the same reason depth>1 does (see above)."""
        from collections import deque

        from ..feeder.worker import EncodedBatch

        if stage_h2d is None:
            stage_h2d = os.environ.get(
                "LOGPARSER_TPU_STAGED_H2D", "1"
            ).strip().lower() not in ("0", "false", "no")
        depth = max(1, depth)
        pending = deque()
        inflight = deque()  # source items of `pending`, for slot release
        try:
            for lines in batches:
                enc = (
                    self._adopt_encoded(lines)
                    if isinstance(lines, EncodedBatch)
                    else self._encode_batch(lines)
                )
                if stage_h2d:
                    enc = self._stage_h2d(enc, emit_views)
                inflight.append(lines)
                if len(pending) >= depth:
                    # Drain the oldest D2H BEFORE enqueueing the next H2D
                    # (link order; the staged upload above is the deliberate
                    # exception), then materialize it while the new batch
                    # computes.
                    fetched = self._fetch_packed(pending.popleft())
                    pending.append(self._dispatch_batch(enc, emit_views))
                    result = self._materialize_packed(fetched)
                    _release_stream_item(inflight.popleft())
                    yield result
                else:
                    pending.append(self._dispatch_batch(enc, emit_views))
            while pending:
                result = self._finish_batch(pending.popleft())
                _release_stream_item(inflight.popleft())
                yield result
        finally:
            # Abandoned stream (close/throw/error): give every undelivered
            # ring slot back so the fabric can wind down instead of
            # wedging producers on an exhausted ring.
            while inflight:
                _release_stream_item(inflight.popleft())

    def _stage_h2d(self, enc, emit_views: Optional[bool]):
        """Begin the async H2D transfer of one encoded batch (double
        buffering: the upload overlaps whatever is already on device).
        Returns the enc tuple extended with the staged device arrays;
        a no-op for host-only parsers."""
        from ..observability import metrics, observe_stage

        if self._executor_for(emit_views) is None:
            return enc
        lines, buf, lengths, overflow, B, padded_b = enc[:6]
        if self._oom_clamp is not None and padded_b > self._oom_clamp:
            # Standing OOM clamp: this batch executes in clamp-sized
            # chunks at fetch time — staging the whole oversized frame
            # would re-create exactly the allocation the clamp forbids.
            return enc
        self._check_device_budget(buf, lengths, B, emit_views)
        t0 = time.perf_counter()
        try:
            if self._mesh is not None:
                # Per-device input sharding ON the H2D edge: each device
                # receives only its batch slice, so the upload fans out
                # across the mesh instead of landing whole on device 0 and
                # resharding inside the jit (the dryrun_multichip feeder
                # idiom promoted to the hot path).
                from ..parallel.mesh import dp_shardings

                (buf_sh, len_sh), _ = dp_shardings(self._mesh)
                staged = (jax.device_put(buf, buf_sh),
                          jax.device_put(lengths, len_sh))
            else:
                staged = (jax.device_put(buf), jax.device_put(lengths))
        except Exception as e:  # noqa: BLE001 — staging is an optimization
            # A staging failure (device OOM mid-upload, lost device)
            # defers placement to dispatch time, where the fault layer
            # classifies and absorbs it — never an abort here.  Still
            # counted + warned-once: a PERSISTENTLY failing staging
            # path silently costs the upload overlap fleet-wide, which
            # must not go dark (details at DEBUG).
            from ..observability import log_warning_once

            metrics().increment("device_stage_fallbacks_total")
            log_warning_once(
                _LOG,
                "device: staged H2D upload failed; batches fall back "
                "to dispatch-time placement "
                "(device_stage_fallbacks_total counts, details at "
                "DEBUG)",
            )
            _LOG.debug("staged H2D failed; deferring to dispatch: %s", e)
            return enc
        observe_stage("h2d_stage", time.perf_counter() - t0, items=B)
        metrics().increment(
            "h2d_staged_bytes_total", int(buf.nbytes + lengths.nbytes)
        )
        return (lines, buf, lengths, overflow, B, padded_b, staged)

    # ------------------------------------------------------------------
    # analytics pushdown (docs/ANALYTICS.md): aggregate queries fuse the
    # reduction into the device pass — the packed columns, view rows and
    # Arrow assembly never happen, and the D2H transfer is the per-batch
    # partial arrays (a few KB) plus one byte per row of fold/reject
    # classification.  Rows the device cannot finish exactly replay the
    # ordinary row path host-side, so every aggregate is bit-identical
    # to aggregating the row-path results.
    # ------------------------------------------------------------------

    def _resolve_agg_spec(self, spec):
        """Normalize a public ``spec`` argument: a built ``AggregateSpec``
        passes through untouched (the service/jobs boundary already
        validated it); an op list or JSON string parses AND validates
        against this parser's fields here, so the parser-level surface
        matches the CONFIG/CLI one."""
        from ..analytics.spec import AggregateSpec, parse_aggregate_config

        if isinstance(spec, AggregateSpec):
            return spec
        parsed = parse_aggregate_config(spec)
        if parsed is None:
            raise ValueError("aggregate: need a spec (op list, JSON "
                             "string, or AggregateSpec)")
        parsed.validate_for(self)
        return parsed

    def aggregate_batch(self, lines: Sequence[Union[bytes, str]], spec):
        """Parse + aggregate one batch entirely on device: returns an
        :class:`~logparser_tpu.analytics.state.AggregateOutcome` whose
        ``state`` holds this batch's partial aggregates (merge partials
        across batches with ``AggregateState.merge``).  ``spec`` is an
        ``AggregateSpec``, an op list, or a JSON string (validated
        against this parser's fields)."""
        spec = self._resolve_agg_spec(spec)
        return self._finish_aggregate(
            self._dispatch_aggregate(self._encode_batch(lines), spec), spec
        )

    def aggregate_blob(self, data: Union[bytes, bytearray, memoryview],
                       spec):
        """:meth:`parse_blob` framing, aggregate delivery (the jobs /
        sidecar ingest shape)."""
        from ..native import encode_blob
        from ..observability import pipeline_stage, record_batch_shape

        spec = self._resolve_agg_spec(spec)
        data = bytes(data)
        lines = _BlobLines(data)
        B = len(lines)
        with pipeline_stage("encode", items=B):
            buf, lengths, overflow = encode_blob(data)
        if buf.shape[0] != B:  # framer/view disagreement: authoritative path
            return self.aggregate_batch(list(lines), spec)
        padded_b = self._bucket(B)
        if padded_b != B:
            buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
            lengths = np.pad(lengths, (0, padded_b - B))
        record_batch_shape(B, padded_b, buf.shape[1], int(lengths.sum()))
        enc = (lines, buf, lengths, overflow, B, padded_b)
        return self._finish_aggregate(
            self._dispatch_aggregate(enc, spec), spec
        )

    def aggregate_batch_stream(self, batches, spec, depth: int = 1):
        """Streamed aggregation: yields one AggregateOutcome per input
        batch, in order, overlapping host accumulation with device work
        (the :meth:`parse_batch_stream` discipline minus the packed D2H
        — there is nothing column-sized to drain).  Items may be line
        lists, or feeder-framed ``EncodedBatch``es (ring slots release
        one accumulation behind delivery, as in the row stream)."""
        from collections import deque

        from ..feeder.worker import EncodedBatch

        spec = self._resolve_agg_spec(spec)
        depth = max(1, depth)
        pending = deque()
        inflight = deque()
        try:
            for lines in batches:
                enc = (
                    self._adopt_encoded(lines)
                    if isinstance(lines, EncodedBatch)
                    else self._encode_batch(lines)
                )
                inflight.append(lines)
                pending.append(self._dispatch_aggregate(enc, spec))
                if len(pending) > depth:
                    outcome = self._finish_aggregate(
                        pending.popleft(), spec
                    )
                    _release_stream_item(inflight.popleft())
                    yield outcome
            while pending:
                outcome = self._finish_aggregate(pending.popleft(), spec)
                _release_stream_item(inflight.popleft())
                yield outcome
        finally:
            while inflight:
                _release_stream_item(inflight.popleft())

    def _agg_executor(self, spec):
        """The compiled aggregate reduction for this parser + spec:
        cached per (canonical spec, CSR slot generation) — a slot regrow
        rebuilds the units, so the reduction rebuilds with them.  None
        when the parser is host-only, the breaker is open, or the spec's
        reduction was compile-demoted (every batch then replays the
        exact row path)."""
        key = spec.canonical_key()
        if key in self._agg_disabled:
            return None
        cached = self._agg_fns.get(key)
        if cached is not None and cached[0] == self.csr_slots:
            return cached[1]
        from ..analytics.device import build_aggregate_fn

        fn, _ = build_aggregate_fn(self, spec)
        self._agg_fns[key] = (self.csr_slots, fn)
        return fn

    def _dispatch_aggregate(self, enc, spec):
        """Asynchronously dispatch the aggregate reduction for one
        encoded batch; faults ride the state tuple to
        :meth:`_finish_aggregate` (same discipline as the row path)."""
        from ..observability import metrics, pipeline_stage

        lines, buf, lengths, overflow, B, padded_b = enc[:6]
        out = None
        fault = None
        fn = self._agg_executor(spec) if self._breaker.allow() else None
        if fn is not None and self._oom_clamp is not None \
                and padded_b > self._oom_clamp:
            # Standing OOM clamp: the row-path fallback executes this
            # batch in clamp-sized chunks instead.
            fn = None
        if fn is not None:
            n_group_ops = sum(
                1 for op in spec.ops
                if op.op in ("count_by", "top_k", "time_bucket")
            )
            self._check_device_budget(
                buf, lengths, B, False, aggregate_group_ops=n_group_ops
            )
            host_kill = np.zeros(padded_b, dtype=bool)
            for i in overflow:
                # Truncated lines: the device saw a prefix — judged
                # host-side, exactly like the row path's overflow demote.
                host_kill[i] = True
            metrics().increment(
                "device_dispatch_total", labels={"views": "agg"}
            )
            with pipeline_stage("device", items=B):
                try:
                    out = fn(jnp.asarray(buf), jnp.asarray(lengths),
                             jnp.int32(B), jnp.asarray(host_kill))
                except Exception as e:  # noqa: BLE001 — absorbed at finish
                    out, fault = None, e
        return (lines, buf, lengths, overflow, B, padded_b, out,
                spec.canonical_key(), fault)

    def _finish_aggregate(self, state, spec):
        """Block on one in-flight aggregate dispatch: fetch the partials,
        accumulate them host-side, and replay every folded row through
        the ordinary row path so the outcome is exact.  Any device fault
        (or no executor at all) downgrades the WHOLE batch to the row
        path — which owns the central fault absorption — and aggregates
        its delivered rows; an aggregate stream never aborts on a device
        failure and never returns an approximate answer."""
        from ..analytics.device import accumulate_partials, fetch_partials
        from ..analytics.state import AggregateOutcome, AggregateState
        from ..observability import metrics, observe_stage

        (lines, buf, lengths, overflow, B, padded_b, out, key,
         fault) = state
        agg = AggregateState(spec)
        fetched = None
        nbytes = 0
        t0 = time.perf_counter()
        if out is not None and fault is None:
            try:
                fetched, nbytes = fetch_partials(out, spec, B, padded_b)
            except Exception as e:  # noqa: BLE001 — classified below
                fetched, fault = None, e
        if fault is not None:
            from ..observability import log_warning_once
            from .device_faults import classify_device_error

            if classify_device_error(fault) == "compile":
                # The REDUCTION does not compile (the row kernel may be
                # fine): demote this spec permanently, keep the parser.
                self._agg_disabled.add(key)
                metrics().increment("analytics_compile_demotions_total")
                log_warning_once(
                    _LOG,
                    "analytics: aggregate reduction failed to compile; "
                    "spec demoted to the exact row-path fallback "
                    "(analytics_compile_demotions_total counts, details "
                    "at DEBUG)",
                )
                _LOG.debug("aggregate compile fault for %s: %s", key, fault)
            else:
                _LOG.debug("aggregate device fault (row-path fallback "
                           "absorbs): %s", fault)
        if fetched is None:
            # Row-path fallback for the whole batch: a fresh dispatch —
            # NOT the ridden fault — so the row executor's own fault
            # layer (bisect/reroute/breaker) judges its own faults.
            result = self._finish_batch(
                (lines, buf, lengths, overflow, B, padded_b, None,
                 self.csr_slots, False, None)
            )
            metrics().increment("analytics_batches_total",
                                labels={"path": "fallback"})
            t1 = time.perf_counter()
            agg.update_from_result(result)
            metrics().observe("analytics_partial_merge_seconds",
                              time.perf_counter() - t1)
            reject_items = [
                (int(i), reason, result.raw_line(int(i)))
                for i, reason in sorted(result.reject_reasons.items())
            ]
            return AggregateOutcome(
                agg, B, result.good_lines, result.bad_lines,
                result.oracle_rows, reject_items,
                device_rows=0, d2h_bytes=0,
            )
        self._breaker.record_success()
        cls = fetched["cls"]
        accumulate_partials(agg, spec, fetched, buf)
        observe_stage("aggregate", time.perf_counter() - t0, items=B)
        metrics().increment("d2h_bytes_total", int(nbytes))
        metrics().increment("analytics_batches_total",
                            labels={"path": "device"})
        # What the row path would have transferred for this batch
        # (packed unit rows + the device-view block) minus what the
        # partials actually cost:
        from .pipeline import packed_row_count

        row_bytes = (
            packed_row_count(self.units) + 4 * self._view_field_count(None)
        ) * padded_b * 4
        metrics().increment(
            "analytics_d2h_bytes_saved_total",
            max(0, int(row_bytes) - int(nbytes)),
        )
        n_device = int(np.count_nonzero(cls == 0))
        fold_rows = np.nonzero(cls == 1)[0]
        reject_rows = np.nonzero(cls == 2)[0]
        reject_items = [
            (int(i), "implausible", _raw_line_bytes(lines[int(i)]))
            for i in reject_rows
        ]
        good = n_device
        bad = len(reject_rows)
        oracle_rows = 0
        if len(fold_rows):
            # Exactness fold: every row the device flagged replays the
            # ordinary row path (rescue, overflow patches, escaped-quote
            # and oracle semantics included) and aggregates from its
            # delivered values — per-row results are independent of
            # batch geometry, so the sub-batch parses identically.
            sub = self.parse_batch(
                [lines[int(i)] for i in fold_rows], emit_views=False
            )
            t1 = time.perf_counter()
            agg.update_from_result(sub)
            metrics().observe("analytics_partial_merge_seconds",
                              time.perf_counter() - t1)
            good += sub.good_lines
            bad += sub.bad_lines
            oracle_rows = sub.oracle_rows
            for j, reason in sub.reject_reasons.items():
                reject_items.append(
                    (int(fold_rows[int(j)]), reason, sub.raw_line(int(j)))
                )
            reject_items.sort(key=lambda item: item[0])
        return AggregateOutcome(
            agg, B, good, bad, oracle_rows, reject_items,
            device_rows=n_device, d2h_bytes=int(nbytes),
        )

    def _start_batch(self, lines: Sequence[Union[bytes, str]]):
        """Encode + pad + asynchronously dispatch the device program.
        Returns the in-flight state ``_finish_batch`` consumes."""
        return self._dispatch_batch(self._encode_batch(lines))

    def _executor_for(self, emit_views: Optional[bool]):
        """The executor an emit_views choice selects: the view-emitting
        product executor by default, the plain one when views are
        disabled (per call or by an empty parser-level demand set).
        None also when the fault layer's circuit breaker has demoted
        the kernel (open / compile-demoted): every batch then takes the
        batched oracle host path — the device twin of the feeder's
        transport demotion (docs/FAULTS.md)."""
        if not self._breaker.allow():
            return None
        if emit_views is None or emit_views:
            return self.device_views_fn()
        return self._jitted

    def _view_field_count(self, emit_views: Optional[bool]) -> int:
        """Trailing device-view rows the chosen executor will emit / 4
        (the budget estimator's input; 0 with views off)."""
        if not (emit_views is None or emit_views):
            return 0
        fields = getattr(self, "_views_fields", None)
        if fields is not None:
            return len(fields)
        return len(self._view_specs())

    def _check_device_budget(self, buf, lengths, B: int,
                             emit_views: Optional[bool],
                             aggregate_group_ops: Optional[int] = None,
                             ) -> None:
        """Pre-allocation device-memory ceiling: validate the padded
        batch's estimated footprint (staged H2D input + packed verdict
        output, ``pipeline.estimate_device_bytes``) against the
        configured budget BEFORE any ``device_put`` — over budget
        answers a structured :class:`DeviceBudgetError`, never an XLA
        RESOURCE_EXHAUSTED (the batch-tier twin of the serving tier's
        frame ceilings; docs/FAULTS.md).  ``aggregate_group_ops`` (the
        analytics pushdown) selects the aggregate-only footprint — no
        view rows, partial-sized D2H — so the budget stops over-
        rejecting aggregate batches that fit comfortably."""
        budget = self.device_bytes_budget
        if not budget:
            return
        from ..observability import metrics
        from .device_faults import DeviceBudgetError
        from .pipeline import estimate_device_bytes

        est = estimate_device_bytes(
            self.units, self._view_field_count(emit_views),
            buf.shape[0], buf.shape[1], lengths.dtype.itemsize,
            aggregate_group_ops=aggregate_group_ops,
        )
        if est > budget:
            metrics().increment("device_budget_rejects_total")
            raise DeviceBudgetError(est, budget, B)

    def _encode_batch(self, lines: Sequence[Union[bytes, str]]):
        from ..observability import pipeline_stage, record_batch_shape

        B = len(lines)
        with pipeline_stage("encode", items=B):
            buf, lengths, overflow = encode_batch(lines)
        # Pad the batch dimension to a bucket so jit recompiles stay bounded.
        padded_b = self._bucket(B)
        if padded_b != B:
            buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
            lengths = np.pad(lengths, (0, padded_b - B))
        record_batch_shape(B, padded_b, buf.shape[1], int(lengths.sum()))
        return list(lines), buf, lengths, overflow, B, padded_b

    def _dispatch_batch(self, enc, emit_views: Optional[bool] = None):
        from ..observability import metrics, pipeline_stage, tracer

        # enc may carry a 7th element: device arrays already staged by
        # _stage_h2d (the overlapped-upload path).
        lines, buf, lengths, overflow, B, padded_b = enc[:6]
        staged = enc[6] if len(enc) > 6 else None
        out = None
        fault = None
        fn = self._executor_for(emit_views)
        if fn is not None and self._oom_clamp is not None \
                and padded_b > self._oom_clamp:
            # Standing OOM clamp: never dispatch above the safe bucket —
            # _fetch_packed executes this batch in clamp-sized chunks.
            fn = None
        if fn is not None:
            if staged is None:
                # (Staged batches were validated in _stage_h2d.)
                self._check_device_budget(buf, lengths, B, emit_views)
            # Label by the executor actually chosen, not the request: a
            # viewless parser's device_views_fn() falls back to the plain
            # executor, and that dispatch must not read as views="on".
            views_on = (
                (emit_views is None or emit_views)
                and bool(getattr(self, "_views_fields", None))
            )
            metrics().increment(
                "device_dispatch_total",
                labels={"views": "on" if views_on else "off"},
            )
            with pipeline_stage("device", items=B):
                try:
                    if staged is not None:
                        out = fn(*staged)
                    else:
                        out = fn(jnp.asarray(buf), jnp.asarray(lengths))
                    if tracer().enabled:
                        # Dispatch is async: make the device stage contain
                        # the actual kernel time instead of misattributing
                        # it to the fetch stage (only when someone is
                        # looking).
                        out = jax.block_until_ready(out)
                except Exception as e:  # noqa: BLE001 — absorbed at fetch
                    # Compile failures and allocation OOMs surface HERE
                    # (jit compiles synchronously at call); the fault
                    # rides the state tuple to _fetch_packed's central
                    # fault policy instead of raising out of the parse.
                    out, fault = None, e
        return (lines, buf, lengths, overflow, B, padded_b, out,
                self.csr_slots, emit_views, fault)

    def _finish_batch(self, state) -> BatchResult:
        return self._materialize_packed(self._fetch_packed(state))

    def _fetch_packed(self, state):
        """Block on the in-flight device result: returns the fetched
        verdicts (packed rows, per-line validity/winner/plausibility)
        ready for :meth:`_materialize_packed`.

        Every device-tier fault lands here — dispatch-time failures ride
        the state tuple, async execution errors surface in the guarded
        fetch — and is ABSORBED by the fault layer
        (:meth:`_absorb_device_fault`): OOMs bisect and retry, wedged or
        otherwise-failed executions reroute the batch to the batched
        oracle host path, compile failures demote the parser key.  The
        only raise left is the pre-allocation
        :class:`~.device_faults.DeviceBudgetError` (a structured reject
        by contract); a parse stream NEVER aborts on a device failure
        (docs/FAULTS.md)."""
        from ..observability import metrics, pipeline_stage

        (lines, buf, lengths, overflow, B, padded_b, out, out_slots,
         emit_views, fault) = state

        from .pipeline import CSR_OVERFLOW_BIT

        while True:
            packed = None
            if fault is None:
                try:
                    if out is not None and out_slots == self.csr_slots:
                        # ONE packed [sum K_i, B] int32 output -> ONE
                        # device->host fetch (transfer round-trips
                        # dominate on tunneled TPU attachments).
                        with pipeline_stage("fetch", items=B):
                            packed = self._guarded_get(out, B)
                    else:
                        # (Re-)dispatch: nothing in flight, a stale CSR
                        # slot layout (another batch's materialization
                        # grew the slots mid-stream), or a clamp/fault
                        # retry path.
                        packed = self._execute_packed(
                            buf, lengths, B, emit_views
                        )
                except Exception as e:  # noqa: BLE001 — classified below
                    fault = e
            out = None
            if fault is not None:
                packed = self._absorb_device_fault(
                    fault, buf, lengths, B, emit_views
                )
                fault = None
            if packed is None:
                valid = np.zeros(B, dtype=bool)
                winner = np.full(B, -1, dtype=np.int64)
                break
            self._breaker.record_success()
            metrics().increment("d2h_bytes_total", int(packed.nbytes))
            # Per-line winner: first registered format whose automaton
            # accepted the line (row_offset row: bit 0 = valid, bit 1 =
            # plausible).  A line is only CLAIMED by format i when no
            # earlier format is still plausible (its separators occur in
            # order) — those lines go to the oracle, which applies the
            # reference's registration-priority semantics with the real
            # backtracking regexes (HttpdLogFormatDissector.java:174-204).
            row0 = np.stack([packed[u.row_offset, :B] for u in self.units])
            # Adaptive CSR: any line with more wildcard segments than the
            # current layout's slots -> double the slots and re-run (a few
            # bounded recompiles replace a per-line oracle cliff).
            if ((row0 & CSR_OVERFLOW_BIT) != 0).any() and self._grow_csr_slots():
                continue
            validity = (row0 & 1) != 0
            plausible = (row0 & 2) != 0
            valid = validity.any(axis=0)
            winner = np.where(valid, validity.argmax(axis=0), -1)
            # Definitely-bad filter: regex-accept implies plausible, so a
            # line implausible for EVERY registered format cannot be
            # accepted by any format regex — the oracle would reject it
            # identically, so it never needs the per-line re-parse.
            plausible_any = plausible.any(axis=0)
            if len(self.units) > 1:
                earlier_plausible = np.cumsum(plausible, axis=0) - plausible
                contested = np.take_along_axis(
                    earlier_plausible,
                    np.maximum(winner, 0)[None, :],
                    axis=0,
                )[0] > 0
                winner = np.where(contested, -1, winner)
                valid = valid & ~contested
            break
        if packed is None or not self._device_covers_all_formats:
            # No device verdict — or formats beyond the compiled prefix
            # exist that the device cannot even judge plausibility for.
            plausible_any = np.ones(B, dtype=bool)
        for i in overflow:
            # Truncated lines: the device only saw a prefix, so its
            # plausibility verdict does not apply — always oracle.
            valid[i] = False
            winner[i] = -1
            plausible_any[i] = True
        return (lines, buf, lengths, B, packed, valid, winner,
                plausible_any, overflow)

    # ------------------------------------------------------------------
    # device fault layer (docs/FAULTS.md): guarded execution, OOM bisect
    # + bucket clamp, wedge deadlines, compile demotion, oracle reroute.
    # ------------------------------------------------------------------

    def _run_guarded(self, work, label: str):
        """Run one blocking device operation under the fault layer's
        guard: the execution deadline (abandonable worker — a wedged XLA
        call expires instead of hanging the pipeline) when armed, and
        raw-error classification into the DeviceFault vocabulary."""
        from .device_faults import (
            DeviceCompileError,
            DeviceExecutionError,
            DeviceFault,
            DeviceOomError,
            classify_device_error,
            run_with_deadline,
        )

        deadline = self.execute_deadline_s
        try:
            if deadline:
                return run_with_deadline(work, deadline, label)
            return work()
        except DeviceFault:
            raise
        except Exception as e:  # noqa: BLE001 — classified
            kind = classify_device_error(e)
            err = {
                "oom": DeviceOomError,
                "compile": DeviceCompileError,
            }.get(kind, DeviceExecutionError)
            raise err(f"{type(e).__name__}: {e}") from e

    def _guarded_get(self, out, n_lines: int):
        """Guarded blocking fetch of an in-flight async dispatch: async
        execution errors surface exactly here, classified like a
        synchronous invoke's; the chaos hook fires once per execution
        at this blocking point."""
        chaos = self._device_chaos
        wedge_s = chaos.on_execute(n_lines) if chaos is not None else None

        def work():
            if wedge_s:
                time.sleep(wedge_s)
            return np.asarray(jax.device_get(out))

        return self._run_guarded(work, "fetch")

    def _invoke_device(self, fn, buf, lengths, n_lines: int):
        """ONE guarded synchronous device execution (dispatch + packed
        fetch) of an already-padded frame.  ``n_lines`` is the REAL
        line count — chaos thresholds key on it."""
        chaos = self._device_chaos
        wedge_s = chaos.on_execute(n_lines) if chaos is not None else None

        def work():
            if wedge_s:
                time.sleep(wedge_s)
            out = fn(jnp.asarray(buf), jnp.asarray(lengths))
            return np.asarray(jax.device_get(out))

        return self._run_guarded(work, "execute")

    def _execute_packed(self, buf, lengths, B: int,
                        emit_views: Optional[bool]):
        """Fresh guarded execution of one encoded batch (the re-dispatch
        path: nothing staged or in flight).  Honors a standing OOM clamp
        by pre-splitting into safe chunks; returns None when every field
        is host-only or the breaker has demoted the kernel.  Raises
        classified DeviceFault errors (absorbed by the caller)."""
        from ..observability import pipeline_stage

        fn = self._executor_for(emit_views)
        if fn is None:
            return None
        clamp = self._oom_clamp
        with pipeline_stage("device", items=B):
            if clamp is not None and B > clamp:
                return self._execute_chunks(fn, buf, lengths, B, clamp)
            return self._invoke_device(fn, buf, lengths, B)

    def _execute_chunks(self, fn, buf, lengths, B: int, chunk: int):
        """Execute rows [0, B) in ``chunk``-sized pieces (the standing
        clamp path) and reassemble the packed verdict columns."""
        parts = [
            self._execute_range(fn, buf, lengths, lo, min(B, lo + chunk), 0)
            for lo in range(0, B, chunk)
        ]
        return np.concatenate(parts, axis=1)

    def _execute_range(self, fn, buf, lengths, lo: int, hi: int,
                       depth: int):
        """Execute rows [lo, hi) padded to their own bucket; on
        RESOURCE_EXHAUSTED, bisect with bounded depth (each retry
        counted on ``device_oom_retries_total``).  Raises DeviceOomError
        when even the policy's minimum bucket OOMs — the caller then
        reroutes the batch to the oracle.  Per-row outputs are
        independent of batch geometry (per-line automata), so the
        reassembled columns are bit-identical to a single-dispatch run —
        the property the device-fault parity drills pin."""
        from ..observability import metrics
        from .device_faults import DeviceOomError

        n = hi - lo
        pb = self._bucket(n)
        sub_buf = buf[lo:hi]
        sub_len = lengths[lo:hi]
        if pb != n:
            sub_buf = np.pad(sub_buf, ((0, pb - n), (0, 0)))
            sub_len = np.pad(sub_len, (0, pb - n))
        try:
            return self._invoke_device(fn, sub_buf, sub_len, n)[:, :n]
        except DeviceOomError:
            pol = self.fault_policy
            if n <= pol.min_bucket or depth >= pol.oom_retries:
                raise
            metrics().increment("device_oom_retries_total")
            self._note_oom(pb)
            mid = lo + (n + 1) // 2
            left = self._execute_range(fn, buf, lengths, lo, mid, depth + 1)
            right = self._execute_range(fn, buf, lengths, mid, hi, depth + 1)
            return np.concatenate([left, right], axis=1)

    def _note_oom(self, failed_bucket: int) -> None:
        """Clamp bookkeeping: after ``oom_clamp_after`` device OOMs the
        parser PERMANENTLY caps its executed bucket below the failing
        size — future batches pre-split before any device_put
        (``device_bucket_clamped`` gauge; warn-once)."""
        from ..observability import log_warning_once, metrics

        self._oom_events += 1
        if self._oom_events < self.fault_policy.oom_clamp_after:
            return
        new_clamp = max(self.fault_policy.min_bucket, failed_bucket // 2)
        if self._oom_clamp is None or new_clamp < self._oom_clamp:
            self._oom_clamp = new_clamp
            metrics().gauge_set("device_bucket_clamped", new_clamp)
            log_warning_once(
                _LOG,
                "device: repeated RESOURCE_EXHAUSTED — max executed "
                "bucket permanently clamped (device_bucket_clamped "
                "gauge; oversized batches now pre-split before "
                "device_put)",
            )

    def _absorb_compile_fault(self, e) -> None:
        """A deterministic compile/lowering failure: demote this parser
        key to the host oracle PERMANENTLY (retrying the same shape
        would fail identically), warn once, count — never raise out of
        the parse."""
        from ..observability import log_warning_once, metrics
        from ..tracing import flight_event

        reg = metrics()
        reg.increment("device_compile_failures_total")
        flight_event("device_compile_fault",
                     error=f"{type(e).__name__}: {e}"[:200])
        if self._breaker.record_fault(permanent=True):
            reg.increment("device_demotions_total",
                          labels={"reason": "compile"})
            log_warning_once(
                _LOG,
                "device: executor compile failed — parser demoted to "
                "the host oracle (results stay exact; "
                "device_compile_failures_total counts, details at "
                "DEBUG)",
            )
        _LOG.debug("device compile fault: %s", e)

    def _absorb_device_fault(self, e, buf, lengths, B: int,
                             emit_views: Optional[bool]):
        """Central device-fault policy (docs/FAULTS.md): classify,
        count, bisect OOMs, and score the circuit breaker — compile
        failures demote the key permanently, repeated transient faults
        demote it until the cool-off (the device twin of
        ``demote_transport``).  Returns the recovered packed block, or
        None to reroute the WHOLE batch to the batched oracle host path
        (byte-identical output either way — the oracle is the exactness
        referee).  Never raises: a device fault costs throughput, never
        the batch."""
        from ..observability import log_warning_once, metrics
        from ..tracing import flight_event
        from .device_faults import DeviceFault, classify_device_error

        reg = metrics()
        kind = classify_device_error(e)
        reg.increment("device_faults_total", labels={"kind": kind})
        # The flight recorder's primary feed: this absorption is
        # deliberately silent on the request path, so the ring is the
        # only per-incident record that survives the process
        # (docs/OBSERVABILITY.md "Flight recorder").
        flight_event("device_fault", fault=kind, batch_rows=B,
                     error=f"{type(e).__name__}: {e}"[:200])
        if kind == "compile":
            self._absorb_compile_fault(e)
            return None
        if kind == "oom" and B > self.fault_policy.min_bucket:
            fn = self._executor_for(emit_views)
            if fn is not None:
                reg.increment("device_oom_retries_total")
                self._note_oom(self._bucket(B))
                try:
                    mid = (B + 1) // 2
                    return np.concatenate([
                        self._execute_range(fn, buf, lengths, 0, mid, 1),
                        self._execute_range(fn, buf, lengths, mid, B, 1),
                    ], axis=1)
                except DeviceFault as e2:
                    if classify_device_error(e2) == "compile":
                        self._absorb_compile_fault(e2)
                        return None
                    kind = classify_device_error(e2)
                    e = e2  # the residual fault falls through to reroute
        # Wedge / transient execute / OOM beyond rescue: reroute this
        # batch to the host oracle and score the breaker.
        reg.increment("device_fault_reroutes_total",
                      labels={"kind": kind})
        if self._breaker.record_fault():
            reg.increment("device_demotions_total",
                          labels={"reason": kind})
            log_warning_once(
                _LOG,
                "device: repeated device faults — kernel demoted to the "
                "host oracle until the breaker cool-off (results stay "
                "exact; device_faults_total{kind} counts, details at "
                "DEBUG)",
            )
        _LOG.debug("device fault rerouted to oracle (%s): %s", kind, e)
        return None

    def _materialize_packed(self, fetched) -> BatchResult:
        from ..observability import metrics, observe_stage

        reg = metrics()
        (lines, buf, lengths, B, packed, valid, winner, plausible_any,
         overflow) = fetched
        columns: Dict[str, Dict[str, np.ndarray]] = {}
        zeros_null = np.zeros(B, dtype=bool)
        # (fid, plan, big_rows, ovf_rows, wide, hi_row) per numeric column
        # with Long-overflow traffic — applied after the overrides dicts
        # exist (see the patch pass below the column loop).
        overflow_patches: List[tuple] = []

        def unit_get(u: FormatUnit, fid: str, comp: str) -> np.ndarray:
            block = packed[u.row_offset : u.row_offset + u.layout.n_rows]
            return u.layout.get(block, fid, comp)[:B]

        # Timestamps are taken unconditionally (perf_counter is ~20ns against
        # a multi-ms batch) so a tracer enabled mid-batch still records real
        # durations; trace.add() itself no-ops when disabled.
        t_columns = time.perf_counter()
        ts_cache: Dict[tuple, tuple] = {}

        def unit_ts(u: FormatUnit, ui: int, plan: _FieldPlan):
            """Decoded timestamp component bundle, cached per (unit, token,
            steps) so N requested outputs of one timestamp decode it once."""
            from .pipeline import ts_group_key

            key = (ui, ts_group_key(plan))
            got = ts_cache.get(key)
            if got is None:
                block = packed[u.row_offset : u.row_offset + u.layout.n_rows]
                comp, ok = u.layout.get_ts_components(block, plan)
                # Third element: the derive() memo sharing epoch/UTC/ISO
                # intermediates across this bundle's requested outputs.
                got = ({k: v[:B] for k, v in comp.items()}, ok[:B], {})
                ts_cache[key] = got
            return got

        for fid in self.requested:
            merged = self.plan_by_id[fid]
            group = self._plan_group(merged)
            if packed is None or group in ("host", "wild"):
                # host: oracle-only.  wild wildcards (.*) deliver
                # exclusively through overrides; wild CONCRETE fields
                # (query.img) get their span column filled directly by
                # _materialize_csr — fresh arrays, it writes into them.
                concrete_wild = (
                    packed is not None
                    and group == "wild"
                    and merged.comp != "*"
                    and not getattr(merged, "attr", "")
                )
                columns[fid] = {
                    "kind": "span",
                    "starts": np.zeros(B, dtype=np.int32),
                    "ends": np.zeros(B, dtype=np.int32),
                    "ok": np.zeros(B, dtype=bool),
                    "null": np.zeros(B, dtype=bool) if concrete_wild
                    else zeros_null,
                }
                continue
            if group == "span":
                col = {
                    "kind": "span",
                    "starts": np.zeros(B, dtype=np.int32),
                    "ends": np.zeros(B, dtype=np.int32),
                    "ok": np.zeros(B, dtype=bool),
                    "null": np.zeros(B, dtype=bool),
                    "amp": np.zeros(B, dtype=bool),
                    "fix": np.zeros(B, dtype=bool),
                    # Which per-row micro-materialization `fix` rows need:
                    # the final uri chain step decides (path: %-repair +
                    # percent-decode; query: %-repair only).
                    "fix_mode": (
                        merged.steps[-1][1]
                        if merged.steps and merged.steps[-1][0] == "uri"
                        else ""
                    ),
                }
            elif group == "obj":
                col = {
                    "kind": "obj",
                    "values": np.full(B, None, dtype=object),
                    "ok": np.zeros(B, dtype=bool),
                    "null": zeros_null,
                }
            else:
                col = {
                    "kind": "numeric",
                    "values": np.zeros(B, dtype=np.int64),
                    "null": np.zeros(B, dtype=bool),
                    "null_zero": np.zeros(B, dtype=bool),
                    "ok": np.zeros(B, dtype=bool),
                }
            for ui, u in enumerate(self.units):
                plan = u.plan_for(fid)
                if not self._unit_decodable(u, fid):
                    continue  # lines won by this unit go through the oracle
                sel = winner == ui
                if not sel.any():
                    continue
                if group == "span":
                    starts_col = unit_get(u, fid, "start")
                    col["starts"] = np.where(sel, starts_col, col["starts"])
                    col["ends"] = np.where(
                        sel, starts_col + unit_get(u, fid, "len"), col["ends"]
                    )
                    col["ok"] = np.where(
                        sel, unit_get(u, fid, "ok") != 0, col["ok"]
                    )
                    col["null"] = np.where(
                        sel, unit_get(u, fid, "null") != 0, col["null"]
                    )
                    col["amp"] = np.where(
                        sel, unit_get(u, fid, "amp") != 0, col["amp"]
                    )
                    col["fix"] = np.where(
                        sel, unit_get(u, fid, "fix") != 0, col["fix"]
                    )
                elif plan.kind == "ts":
                    comp, ok, memo = unit_ts(u, ui, plan)
                    values = timefields.derive(
                        comp, plan.comp, memo,
                        locale=getattr(plan.meta, "locale", None),
                    )
                    # A non-geo fill on a (possibly geo-shared, mixed-
                    # format) obj column: the Arrow dict/typed fast paths
                    # only see geo-written state and would null these
                    # rows — disable them for this column, either order.
                    col["mixed_fill"] = True
                    col["values"] = np.where(sel, values, col["values"])
                    col["ok"] = np.where(sel, ok, col["ok"])
                elif plan.kind == "geo":
                    from .pipeline import geo_group_key

                    _, column, table = plan.meta
                    block = packed[u.row_offset : u.row_offset + u.layout.n_rows]
                    key = geo_group_key(plan)
                    rows_idx = u.layout.get(block, key, "row")[:B]
                    ok = (u.layout.get(block, key, "ok") != 0)[:B]
                    arr = table.arrays[column][rows_idx]
                    if column in table.vocabs:
                        vocab = table.vocab_arrays[column]
                        values = vocab[arr]
                        # Keep the vocab CODES for the Arrow bridge: geo
                        # strings are low-cardinality, so the column can
                        # build as dictionary.take(codes) with zero
                        # per-row inference.  A second fill from a
                        # DIFFERENT vocab (mixed-format batch over
                        # distinct .mmdb tables) disables the fast path.
                        if "dict_codes" not in col:
                            col["dict_codes"] = np.full(B, -1, dtype=np.int64)
                            col["dict_values"] = vocab
                        if col.get("dict_values") is vocab:
                            col["dict_codes"] = np.where(
                                sel, arr.astype(np.int64), col["dict_codes"]
                            )
                        else:
                            col["dict_values"] = None
                    elif arr.dtype.kind == "f":
                        values = arr.astype(object)
                        values[np.isnan(arr)] = None
                        self._geo_typed_fill(col, sel, arr.astype(np.float64),
                                             np.isnan(arr), "f")
                    else:
                        values = arr.astype(object)
                        values[arr < 0] = None
                        self._geo_typed_fill(col, sel, arr.astype(np.int64),
                                             arr < 0, "i")
                    col["values"] = np.where(sel, values, col["values"])
                    col["ok"] = np.where(sel, ok, col["ok"])
                elif plan.kind == "muid":
                    from .pipeline import muid_group_key

                    key = muid_group_key(plan)
                    ok = unit_get(u, key, "ok") != 0
                    col["mixed_fill"] = True  # see the ts branch
                    if plan.comp == "ip":
                        u32 = (
                            unit_get(u, key, "ip").astype(np.int64)
                            & 0xFFFFFFFF
                        )
                        # Vectorized dotted-quad: a 256-entry octet-string
                        # vocab + object-array concatenation (no per-row
                        # Python loop).
                        octs = _OCTET_STRINGS
                        dot = np.full(B, ".", dtype=object)
                        vals = (
                            octs[(u32 >> 24) & 255] + dot
                            + octs[(u32 >> 16) & 255] + dot
                            + octs[(u32 >> 8) & 255] + dot
                            + octs[u32 & 255]
                        )
                        values = np.where(ok, vals, None)
                        col["values"] = np.where(sel, values, col["values"])
                    else:
                        comp_row = {
                            "epoch": "time", "processid": "pid",
                            "counter": "counter", "threadindex": "thread",
                        }[plan.comp]
                        values = (
                            unit_get(u, key, comp_row).astype(np.int64)
                            & 0xFFFFFFFF
                        )
                        if plan.comp == "epoch":
                            values = values * 1000
                        col["values"] = np.where(sel, values, col["values"])
                    col["ok"] = np.where(sel, ok, col["ok"])
                else:  # long / secmillis
                    is_null = unit_get(u, fid, "null") != 0
                    big = unit_get(u, fid, "big") != 0
                    hi_row = unit_get(u, fid, "hi")
                    values, ovf, wide = postproc.combine_long_limbs(
                        hi_row,
                        unit_get(u, fid, "lo"),
                        unit_get(u, fid, "d18"),
                        unit_get(u, fid, "lo_digits"),
                        is_null,
                    )
                    # Overflow class (reference FORMAT_NUMBER has no width
                    # bound): 19-digit values beyond Long.MAX (exact in
                    # the uint64 frame) and >19-digit runs (hi row carries
                    # the span for a host byte-patch).  Both deliver via
                    # the post-loop patch, not the int64 column; is_null
                    # never overlaps (a dash is 1 byte).
                    ovf = ovf & ~big & ~is_null
                    row_ok = unit_get(u, fid, "ok") != 0
                    of_sel = sel & row_ok & valid & (ovf | big)
                    if of_sel.any():
                        overflow_patches.append((
                            fid, plan, of_sel & big, of_sel & ovf,
                            wide, hi_row,
                        ))
                    if plan.kind == "secmillis":
                        values = values * 1000 + unit_get(u, fid, "milli")
                    if plan.scale != 1:
                        values = values * plan.scale
                    if plan.null_mode == "zero_null":
                        is_null = is_null | (values == 0)
                    col["values"] = np.where(sel, values, col["values"])
                    col["null"] = np.where(sel, is_null, col["null"])
                    col["ok"] = np.where(sel, row_ok, col["ok"])
                    if plan.null_mode == "dash_zero":
                        col["null_zero"] = np.where(sel, True, col["null_zero"])
            columns[fid] = col
        observe_stage("columns", time.perf_counter() - t_columns, items=B)

        # Host fallback: invalid lines entirely; host-only fields for every line.
        # Numeric coercion follows the kind of the format that won the
        # line (a field can be numeric under one format and a plain
        # string under another); unknown winner -> merged kind.  A winner
        # that resolves the field as "host" (multi-producer) dispatches
        # on the producing dissector's setter casts instead — the
        # resolution is line-invariant per (fields, winner) and compiled
        # into delivery_plan below.
        overrides: Dict[str, Any] = {
            fid: (_LazyWildcard() if fid.endswith(".*") else {})
            for fid in columns
        }
        # Reference Long-overflow delivery (the former largest self-imposed
        # reject class): 19-digit values beyond Long.MAX deliver their
        # exact frame value, >19-digit runs are byte-patched from the
        # buffer — both as overrides, replaying what the oracle's
        # STRING-cast path would store, WITHOUT a per-line re-parse.
        # Ineligible plans (chained/scaled/zero_null/odd casts) and big
        # spans whose unchecked tail turns out non-digit demote to the
        # full oracle, which applies the exact semantics.
        from .pipeline import _SPAN_BITS

        demoted: set = set()
        span_mask = (1 << _SPAN_BITS) - 1
        for fid, plan, big_rows, ovf_rows, wide, hi_row in overflow_patches:
            mode = self._overflow_delivery.get(fid, "oracle")
            eligible = (
                plan.kind == "long" and not plan.steps and plan.scale == 1
                and plan.null_mode != "zero_null" and mode in ("int", "null")
            )
            if not eligible:
                demoted.update(
                    int(i) for i in np.nonzero(big_rows | ovf_rows)[0]
                )
                continue
            ov = overrides[fid]
            if mode == "null":
                # LONG-only casts: Long.parseLong fails beyond the range,
                # the null is delivered (policy ALWAYS), the record reads
                # None.
                for i in np.nonzero(big_rows | ovf_rows)[0]:
                    ov[int(i)] = None
                continue
            for i in np.nonzero(ovf_rows)[0]:
                ov[int(i)] = int(wide[i])
            for i in np.nonzero(big_rows)[0]:
                i = int(i)
                word = int(hi_row[i])
                raw = bytes(
                    buf[i, word & span_mask:
                        (word & span_mask) + (word >> _SPAN_BITS)]
                )
                if raw.isdigit():
                    ov[i] = int(raw)
                else:
                    # The tail beyond the 19-byte device window is not all
                    # digits: the token regex would reject — full oracle.
                    demoted.add(i)
                    ov.pop(i, None)
        for i in demoted:
            valid[i] = False
            winner[i] = -1
            plausible_any[i] = True
            for fid in self.requested:
                overrides[fid].pop(i, None)
        # Invalid AND implausible-for-all-formats: definitely bad, counted
        # without an oracle visit (the single biggest fallback cost on
        # hostile corpora — garbage lines are almost never plausible).
        inv = ~valid
        bad = int(np.count_nonzero(inv & ~plausible_any))
        invalid_rows = set(
            int(i) for i in np.nonzero(inv & plausible_any)[0]
        )
        # Per-row reject ledger: every row that ends the batch invalid
        # carries a stable reason (the jobs reject channel and the fuzz
        # suite both pin the vocabulary): "implausible" = no format even
        # plausible, rejected without an oracle visit; "oracle_reject" =
        # the oracle parsed and refused (DissectionFailure);
        # "oracle_error" = the oracle engine ITSELF failed on the line.
        reject_reasons: Dict[int, str] = {
            int(i): "implausible" for i in np.nonzero(inv & ~plausible_any)[0]
        }
        # Rows the oracle must visit: lines no automaton accepted (but some
        # format could still plausibly match), plus lines whose winning
        # format can't supply every requested field on device.
        need_oracle = set(invalid_rows)
        for ui, flds in enumerate(self._unit_oracle_fields):
            if flds:
                need_oracle.update(int(r) for r in np.nonzero(winner == ui)[0])
        # Batched rescue, started BEFORE the CSR materialization: the
        # rejected rows are framed once and parsed through the reused
        # per-format fastline program; on a multi-worker assembly pool
        # the parse runs on a pool thread and overlaps the numpy-heavy
        # CSR stage below (rescue no longer serializes behind the whole
        # materialization).  CSR-failed rows (rare) are parsed inline
        # afterwards.
        t_submit = time.perf_counter()
        engine_before = self._oracle_engine_tally()
        rescue_rows = sorted(need_oracle)
        collect_rescue = self._start_rescue(rescue_rows, lines)
        rescue_wall = time.perf_counter() - t_submit
        # Device CSR wildcards (query params): build the per-line override
        # values from the packed segment table; a resilientUrlDecode failure
        # is exactly a line the host engine fails, so those rows drop to
        # invalid and take the oracle (which rejects them identically).
        t_csr = time.perf_counter()
        csr_failed = self._materialize_csr(
            packed, winner, valid, overrides, columns, buf, B
        )
        extra_rows: List[int] = []
        for i in csr_failed:
            valid[i] = False
            winner[i] = -1
            for fid in self.requested:
                overrides[fid].pop(i, None)
            invalid_rows.add(i)
            if i not in need_oracle:
                need_oracle.add(i)
                extra_rows.append(i)
        observe_stage("csr_materialize", time.perf_counter() - t_csr, items=B)
        # Escaped-quote decode accounting (round 18): lines the device
        # claimed THROUGH the escape-parity mask — the winning unit's
        # ESC_QUOTE_BIT on rows that survived every demotion above.
        # These are exactly the lines that pre-round-18 routed to the
        # host rescue as device_reject.
        escaped_quote_rows = 0
        if packed is not None and self.units:
            from .pipeline import ESC_QUOTE_BIT

            esc_bits = np.stack([
                (packed[u.row_offset, :B] & ESC_QUOTE_BIT) != 0
                for u in self.units
            ])
            esc_won = np.take_along_axis(
                esc_bits, np.maximum(winner, 0)[None, :], axis=0
            )[0]
            escaped_quote_rows = int(np.count_nonzero(esc_won & valid))
            if escaped_quote_rows:
                reg.increment(
                    "device_escaped_quote_lines_total", escaped_quote_rows
                )
        # Routed-line accounting by reject class (batch granularity): WHY
        # each line left the device-only path.  overflow = truncated lines
        # the device judged on a prefix; device_reject = no automaton
        # accepted but some format stayed plausible; host_fields = the
        # winning format cannot supply every requested field on device.
        overflow_rows = {int(i) for i in overflow if 0 <= int(i) < B}
        rescue_reasons = {"overflow": 0, "device_reject": 0, "host_fields": 0}
        if bad:
            reg.increment("definitely_bad_lines_total", bad)
        if need_oracle:
            # Disjoint by construction (overflow rows are forced invalid
            # in _fetch_packed; the explicit exclusions keep the three
            # classes summing to len(need_oracle) even if that drifts).
            rescue_reasons["overflow"] = len(overflow_rows & need_oracle)
            rescue_reasons["device_reject"] = len(
                invalid_rows - overflow_rows
            )
            rescue_reasons["host_fields"] = len(
                need_oracle - invalid_rows - overflow_rows
            )
            for reason, n in rescue_reasons.items():
                if n:
                    reg.increment("oracle_routed_lines_total", n,
                                  labels={"reason": reason})
            # Per-field census of the host_fields residual: which requested
            # fields are still forcing whole-line oracle routing.  A row on
            # the host_fields path charges every oracle field of its winning
            # unit — the set the next device lane must cover to free it.
            if rescue_reasons["host_fields"]:
                hf = np.fromiter(
                    (need_oracle - invalid_rows - overflow_rows),
                    dtype=np.int64,
                )
                hf_win = winner[hf]
                cnt = np.bincount(
                    hf_win[hf_win >= 0], minlength=len(self.units)
                )
                for ui, flds in enumerate(self._unit_oracle_fields):
                    n_unit = int(cnt[ui]) if ui < cnt.shape[0] else 0
                    if not n_unit or not flds:
                        continue
                    for fid in flds:
                        reg.increment(
                            "host_field_lines_total", n_unit,
                            labels={"field": _bounded_field_label(fid)},
                        )
        t_oracle = time.perf_counter()
        oracle_rows_sorted = sorted(need_oracle)
        results_by_row = dict(zip(rescue_rows, collect_rescue()))
        if extra_rows:
            extra_rows.sort()
            results_by_row.update(zip(
                extra_rows,
                self._run_oracle_many([lines[i] for i in extra_rows]),
            ))
        oracle_results = [results_by_row[i] for i in oracle_rows_sorted]
        # Fully-resolved per-(fields, winner) delivery plan: field split,
        # override dict, and the coercion decision (device plan group +
        # setter casts) are all line-invariant — resolving them per VALUE
        # was ~40% of the rescue stage on top of the raw parses, which is
        # exactly the kind of drift the bench's rescue-model validation
        # (combined_rescue config) exists to catch.
        plan_cache: Dict[Tuple[bool, int], Tuple[list, list]] = {}

        def delivery_plan(fields, w, is_invalid):
            # Keyed on what DETERMINES the fields list ((is_invalid, w)),
            # not its identity — id() is only stable because both lists
            # happen to be parser-lifetime attributes today.
            key = (is_invalid, w)
            got = plan_cache.get(key)
            if got is None:
                concrete, wild = [], []
                for fid in fields:
                    if fid.endswith(".*"):
                        wild.append((fid, overrides[fid], fid[:-1]))
                        continue
                    plan = (
                        self.units[w].plan_for(fid) if w >= 0
                        else self.plan_by_id[fid]
                    )
                    flags = self._cast_flags.get(fid)
                    if self._plan_group(plan) == "numeric":
                        mode = "num"
                    elif flags and (flags[0] or flags[1]):
                        # LONG-then-DOUBLE fallthrough, like _coerce_casts
                        # (same _cast_flags source).
                        mode = flags
                    else:
                        mode = "plain"
                    concrete.append((fid, overrides[fid], mode))
                got = (concrete, wild)
                plan_cache[key] = got
            return got

        oracle_rescued = oracle_rejected = engine_errors = 0
        for i, values in zip(oracle_rows_sorted, oracle_results):
            is_invalid = i in invalid_rows
            fields_needed = (
                self.requested
                if is_invalid
                else self._unit_oracle_fields[winner[i]]
            )
            if values is None or isinstance(values, OracleEngineError):
                # None = the oracle parsed and refused (the reference's
                # bad-line verdict).  OracleEngineError = the oracle
                # ITSELF failed — surfaced as a counted, reasoned reject
                # (never a raise, never a silent None): a device-valid
                # line keeps its device columns with the host fields
                # unresolved; an invalid line rejects as oracle_error.
                oracle_rejected += 1
                if isinstance(values, OracleEngineError):
                    engine_errors += 1
                    from ..observability import log_warning_once

                    # STATIC warn-once key (per-line error text would
                    # grow the warn-once table without bound on a
                    # hostile corpus); the exact error rides the reject
                    # table and DEBUG.
                    log_warning_once(
                        _LOG,
                        "host oracle engine failed on one or more lines;"
                        " surfaced as oracle_error rejects (details at "
                        "DEBUG / in the reject channel)",
                    )
                    _LOG.debug("oracle engine fault on row %d: %s",
                               i, values.error)
                if is_invalid:
                    bad += 1
                    reject_reasons[i] = (
                        "oracle_error"
                        if isinstance(values, OracleEngineError)
                        else "oracle_reject"
                    )
                continue
            if is_invalid:
                valid[i] = True
                oracle_rescued += 1
            concrete, wild = delivery_plan(
                fields_needed, int(winner[i]), is_invalid
            )
            for fid, ov, mode in concrete:
                v = values.get(fid)
                if v is None or mode == "plain":
                    ov[i] = v
                elif mode == "num":
                    try:
                        ov[i] = int(v)
                    except (TypeError, ValueError):
                        ov[i] = None
                else:  # setter casts: LONG then DOUBLE then raw
                    ov[i] = _apply_setter_casts(v, mode[0], mode[1])
            for fid, ov, prefix in wild:
                # Wildcard target: deliver {relative.name: value} built
                # from every concrete field under the prefix (the oracle
                # stores them under their full TYPE:path names).
                ov[i] = {
                    k[len(prefix):]: v
                    for k, v in values.items()
                    if k.startswith(prefix)
                }
        # oracle_fallback measures the wall time rescue ADDED to the batch:
        # submit/framing cost plus the blocked wait + delivery — parse
        # time hidden under the CSR stage by the pool thread is excluded
        # (that overlap is the point of the batched rescue).
        rescue_wall += time.perf_counter() - t_oracle
        observe_stage("oracle_fallback", rescue_wall, items=len(need_oracle))
        if oracle_rescued:
            reg.increment("oracle_rescued_lines_total", oracle_rescued)
        if oracle_rejected:
            reg.increment("oracle_rejected_lines_total", oracle_rejected)
        if engine_errors:
            reg.increment("oracle_engine_errors_total", engine_errors)
        self._fold_oracle_engine_tally(engine_before)

        good = int(B - bad)
        reg.increment("good_lines_total", good)
        if bad:
            reg.increment("bad_lines_total", bad)
        # Device-emitted Arrow view rows (4 per span field, after the unit
        # rows): handed to the Arrow bridge, which interleaves them into
        # string_view structs without touching the byte buffer.  Overflow
        # rows are flagged dirty — the device judged a truncated prefix,
        # so its views for those rows are not trustworthy.
        device_views = None
        dirty_rows = None
        view_block = None
        view_fields = getattr(self, "_views_fields", None)
        if packed is not None and view_fields:
            k0 = (
                self.units[-1].row_offset + self.units[-1].layout.n_rows
                if self.units else 0
            )
            if packed.shape[0] >= k0 + 4 * len(view_fields):
                # Keep ONLY the trailing view block alive on the result
                # (contiguous copy): pinning the whole packed fetch would
                # retain several MB of unit rows the bridge never reads.
                view_block = packed[k0: k0 + 4 * len(view_fields)].copy()
                device_views = {
                    fid: 4 * i for i, fid in enumerate(view_fields)
                }
                dirty_rows = np.asarray(
                    [i for i in overflow if i < B], dtype=np.int64
                )
        result = BatchResult(
            # _encode_batch already listed the caller's lines; _BlobLines
            # stays lazy (its rows materialize only when indexed).
            lines, buf[:B], lengths[:B], valid, columns, overrides,
            good, bad, format_index=winner[:B], oracle_rows=len(need_oracle),
            packed=view_block, device_views=device_views,
            dirty_rows=dirty_rows, assembly_pool=self.assembly_pool(),
        )
        # Rescue composition for this batch: per-reason routed counts and
        # the wall seconds the rescue added (the bench's stdout
        # composition line and the smoke tool read these).
        result.rescue_reasons = rescue_reasons
        result.rescue_wall_s = rescue_wall
        result.escaped_quote_rows = escaped_quote_rows
        result.reject_reasons = reject_reasons
        result.oracle_row_ids = np.asarray(oracle_rows_sorted, dtype=np.int64)
        return result

    def _materialize_csr(
        self, packed, winner, valid, overrides, columns, buf, B
    ) -> set:
        """Materialize device CSR wildcard groups (query params / cookies /
        set-cookies) from the packed segment table.

        Vectorized: emitted segments are flattened with numpy gathers into
        one flat byte buffer per (names, values); per-segment Python work is
        one bytes-slice decode.  Concrete fields (``query.img``) are matched
        by name and written straight into their span COLUMN (no per-row
        objects at all); wildcard ``.*`` fields build their per-row dicts
        from the flat buffers.  Only rows that need per-value Python —
        resilientUrlDecode (``%``/``+`` values), uri-chain name %-repair,
        or whitespace/non-ASCII trimming at cookie name/value edges — take
        the per-row fallback loop.  Returns rows whose value decode failed
        (the host engine fails those lines; caller invalidates them so the
        oracle re-rejects identically)."""
        from .pipeline import csr_group_key

        failed: set = set()
        if packed is None:
            return failed
        L = buf.shape[1]
        buf_flat = buf.reshape(-1)
        for ui, u in enumerate(self.units):
            qs_plans = [
                (fid, u.plan_for(fid))
                for fid in self.requested
                if u.plan_for(fid).kind == "qscsr"
                and self._unit_decodable(u, fid)
            ]
            if not qs_plans:
                continue
            rows = np.nonzero((winner == ui) & valid)[0]
            if rows.size == 0:
                continue
            block = packed[u.row_offset : u.row_offset + u.layout.n_rows]
            by_key: Dict[str, List] = {}
            for fid, p in qs_plans:
                by_key.setdefault(csr_group_key(p), []).append((fid, p))
            for key, flist in by_key.items():
                ok = u.layout.get(block, key, "ok") != 0
                uri_chain = bool(flist[0][1].steps)
                cookie = flist[0][1].meta == "cookie"
                setcookie = flist[0][1].meta == "setcookie"
                K = u.layout.csr_slots

                def mat(comp: str) -> np.ndarray:
                    return np.stack([
                        u.layout.get(block, key, f"s{k}_{comp}")[:B][rows]
                        for k in range(K)
                    ])

                SS, NL, VS, VL = mat("start"), mat("nlen"), mat("vstart"), mat("vlen")
                HE = mat("eq").astype(bool)
                DC = mat("dec").astype(bool)
                ND = mat("ndec").astype(bool)
                ok_r = ok[rows]
                # A segment is emitted iff its name is non-empty: empty
                # slots pack nlen 0, and "=value" segments (empty name)
                # match nothing — QueryStringFieldDissector skips them.
                # Set-cookie additionally requires the device emit bit.
                emit = (NL > 0) & ok_r[None, :]
                if setcookie:
                    emit &= HE

                # Segments needing per-value Python: url-decode (%/+ in
                # value), uri-chain name %-repair, or cookie/set-cookie
                # whitespace-or-non-ASCII trim at name/value edges (host
                # str.strip() also eats \x1c-\x1f and unicode whitespace;
                # >= 0x80 edge bytes conservatively take the slow path).
                def edge(S, N):
                    has = N > 0
                    a = rows[None, :] * L + S
                    first = buf_flat[np.where(has, a, 0)]
                    last = buf_flat[np.where(has, a + N - 1, 0)]
                    e = (first <= 0x20) | (first >= 0x80)
                    e |= (last <= 0x20) | (last >= 0x80)
                    return has & e

                def direct_hard(fl):
                    # Direct-capture rows whose flagged values the
                    # vectorized left-to-right decode cannot prove: a
                    # '%' without two in-segment hex digits (the
                    # un-repaired host decoder may chop it, raise, or
                    # read %uXXXX as UTF-16) or a raw byte >= 0x80.
                    hard = np.zeros(fl.shape[1], dtype=bool)
                    fk, fj = np.nonzero(fl)
                    if fk.size == 0:
                        return hard
                    v_l = np.where(HE[fk, fj], VL[fk, fj], 0).astype(
                        np.int64
                    )
                    f_off = np.zeros(fk.size + 1, dtype=np.int64)
                    np.cumsum(v_l, out=f_off[1:])
                    gidx = np.repeat(
                        (rows[fj] * L + VS[fk, fj]).astype(np.int64)
                        - f_off[:-1], v_l,
                    ) + np.arange(int(f_off[-1]), dtype=np.int64)
                    _, _, bad = _qs_value_decode(buf_flat[gidx], f_off)
                    hard[fj[bad]] = True
                    return hard

                if setcookie:
                    flag = edge(SS, NL)
                elif cookie:
                    flag = DC | edge(SS, NL) | edge(VS, VL)
                elif uri_chain:
                    # Names needing %-repair keep the per-row loop;
                    # flagged VALUES decode in the vectorized lane below
                    # (device-valid uri-chain segments are clean ASCII
                    # by the split discipline, so the left-to-right
                    # rule is exact).
                    flag = ND
                else:
                    flag = DC & direct_hard(DC & emit)[None, :]
                flag &= emit
                row_flag = flag.any(axis=0)
                vrows = rows[~row_flag]
                py_rows = rows[row_flag]

                need_dicts = any(p.comp == "*" for _, p in flist)
                dicts: Dict[int, Optional[Dict[str, str]]] = {}

                # ---- vectorized path: flatten emitted segments ----------
                emv = emit[:, ~row_flag]
                pr, pk = np.nonzero(emv.T)  # row-major: slot order per row
                n_seg = pr.size
                nb, non = b"", np.zeros(1, dtype=np.int64)
                vb, nov = b"", np.zeros(1, dtype=np.int64)
                seg_high = np.zeros(0, dtype=bool)
                if n_seg:
                    sub = (pk, pr)
                    s_row = vrows[pr]
                    s_ss = SS[:, ~row_flag][sub]
                    s_nl = NL[:, ~row_flag][sub]
                    s_vs = VS[:, ~row_flag][sub]
                    s_vl = np.where(
                        HE[:, ~row_flag][sub] | setcookie,
                        VL[:, ~row_flag][sub], 0,
                    )

                    def flat(starts, lens):
                        off = np.zeros(len(lens) + 1, dtype=np.int64)
                        np.cumsum(lens, out=off[1:])
                        idx = np.repeat(
                            s_row * L + starts - off[:-1], lens
                        ) + np.arange(int(off[-1]), dtype=np.int64)
                        return buf_flat[idx].tobytes(), off

                    nb, non = flat(s_ss, s_nl)
                    nb_np = np.frombuffer(nb, dtype=np.uint8)
                    if nb_np.size:
                        seg_high = np.add.reduceat(
                            (nb_np >= 0x80).astype(np.int64), non[:-1]
                        ) > 0
                    else:
                        seg_high = np.zeros(n_seg, dtype=bool)
                    if need_dicts:
                        vb, nov = flat(s_vs, s_vl)
                else:
                    s_row = s_ss = s_nl = s_vs = s_vl = np.empty(
                        0, dtype=np.int64
                    )

                # ---- vectorized value decode: flagged (%/+/encode-set)
                # values of query chains decode here with compact
                # gathers — the exact fix+resilientUrlDecode result for
                # the segment classes proven above; only name repair,
                # cookie edge trims, and hard direct escapes still pay
                # the per-row loop.
                dec_pos = np.full(n_seg, -1, dtype=np.int64)
                darr = np.zeros(0, dtype=np.uint8)
                d_off = np.zeros(1, dtype=np.int64)
                if n_seg and not (cookie or setcookie):
                    s_dc = DC[:, ~row_flag][sub]
                    dec_idx = np.nonzero(s_dc)[0]
                    if dec_idx.size:
                        dec_pos[dec_idx] = np.arange(dec_idx.size)
                        fl_l = s_vl[dec_idx].astype(np.int64)
                        f_off = np.zeros(dec_idx.size + 1, dtype=np.int64)
                        np.cumsum(fl_l, out=f_off[1:])
                        gidx = np.repeat(
                            (s_row[dec_idx] * L + s_vs[dec_idx]).astype(
                                np.int64
                            ) - f_off[:-1], fl_l,
                        ) + np.arange(int(f_off[-1]), dtype=np.int64)
                        darr, d_off, _ = _qs_value_decode(
                            buf_flat[gidx], f_off
                        )
                        if need_dicts:
                            # Splice the decoded (UTF-8-transcoded)
                            # bytes into the flat wildcard value buffer
                            # in place of the raw spans.
                            uarr, u_off = _latin1_to_utf8(darr, d_off)
                            vb_np = np.frombuffer(vb, dtype=np.uint8)
                            lens = np.diff(nov)
                            lens2 = lens.copy()
                            lens2[dec_idx] = np.diff(u_off)
                            nov2 = np.zeros_like(nov)
                            np.cumsum(lens2, out=nov2[1:])
                            new_vb = np.empty(int(nov2[-1]), dtype=np.uint8)
                            keep_i = np.nonzero(~s_dc)[0]
                            _seg_scatter(new_vb, nov2[keep_i], vb_np,
                                         nov[keep_i], lens[keep_i])
                            _seg_scatter(new_vb, nov2[dec_idx], uarr,
                                         u_off[:-1], lens2[dec_idx])
                            vb, nov = new_vb.tobytes(), nov2

                def match_comp(comp: str) -> np.ndarray:
                    # Byte-wise name match with ASCII case fold; Python
                    # strings are never built for the common case.
                    # Segments containing ANY high byte decode individually
                    # regardless of byte length: host str.lower() can
                    # change the UTF-8 length (e.g. U+212A Kelvin sign,
                    # 3 bytes -> 'k', 1 byte), so a raw-length pre-filter
                    # would silently miss them.
                    comp_b = comp.encode("utf-8")
                    if n_seg == 0 or len(comp_b) == 0:
                        return np.empty(0, dtype=np.int64)
                    mlen = np.nonzero((s_nl == len(comp_b)) & ~seg_high)[0]
                    out = mlen
                    if mlen.size:
                        idx = (
                            (s_row * L + s_ss)[mlen][:, None]
                            + np.arange(len(comp_b))
                        )
                        g = buf_flat[idx]
                        upper = (g >= 0x41) & (g <= 0x5A)
                        folded = np.where(upper, g | 0x20, g)
                        target = np.frombuffer(comp_b, dtype=np.uint8)
                        out = mlen[(folded == target).all(axis=1)]
                    extra = [
                        j
                        for j in np.nonzero(seg_high)[0].tolist()
                        if nb[non[j] : non[j + 1]]
                        .decode("utf-8", "replace").lower() == comp
                    ]
                    if extra:
                        out = np.concatenate(
                            [out, np.asarray(extra, dtype=np.int64)]
                        )
                        out.sort()
                    return out

                match_cache: Dict[str, np.ndarray] = {}
                attrs_cache: Dict[str, dict] = {}
                for fid, p in flist:
                    if p.comp == "*":
                        continue
                    m = match_cache.get(p.comp)
                    if m is None:
                        m = match_cache[p.comp] = match_comp(p.comp)
                    if getattr(p, "attr", ""):
                        if isinstance(p.attr, tuple):
                            # Remapped screen-resolution param: split the
                            # matched segment's value host-side.
                            self._deliver_sres_attr(
                                fid, p, m, s_row, s_vs, s_vl, buf, overrides,
                                decoded=(dec_pos, darr, d_off),
                            )
                            continue
                        # Per-cookie attribute: parse the matched cookie's
                        # text once per row (host parse_attrs — the exact
                        # per-line semantics) and deliver via overrides.
                        self._deliver_setcookie_attr(
                            fid, p, m, s_row, s_vs, s_vl, buf, overrides,
                            attrs_cache,
                        )
                        continue
                    # Concrete field -> span column writes (duplicate rows:
                    # numpy fancy assignment keeps the LAST segment, the
                    # host's overwrite order).
                    col = columns[fid]
                    col["ok"][vrows] = True
                    col["null"][vrows] = True
                    if m.size:
                        mr = s_row[m]
                        col["starts"][mr] = s_vs[m]
                        col["ends"][mr] = s_vs[m] + s_vl[m]
                        col["null"][mr] = False
                        # Rows whose LAST matched segment was decoded
                        # deliver the decoded value via override — span
                        # columns can only point at raw buffer bytes.
                        last = np.ones(m.size, dtype=bool)
                        if m.size > 1:
                            last[:-1] = mr[:-1] != mr[1:]
                        for j in m[last & (dec_pos[m] >= 0)].tolist():
                            jj = int(dec_pos[j])
                            overrides[fid][int(s_row[j])] = bytes(
                                darr[d_off[jj]:d_off[jj + 1]]
                            ).decode("latin-1")

                # ---- per-row fallback: decode/repair/trim segments ------
                if py_rows.size:
                    self._materialize_csr_slow(
                        py_rows, rows, ok, SS, NL, HE, DC, ND, VS, VL,
                        uri_chain, cookie, setcookie, buf, dicts, failed,
                        need_dicts, flist, overrides, columns,
                    )

                if need_dicts:
                    for fid, p in flist:
                        if p.comp != "*":
                            continue
                        tgt = overrides[fid]
                        if isinstance(tgt, _LazyWildcard):
                            if vrows.size:
                                tgt.add_chunk(
                                    vrows, s_row, nb, non, vb, nov, seg_high
                                )
                            tgt.eager.update(dicts)
                        else:  # pragma: no cover — defensive
                            for i, d in dicts.items():
                                tgt[i] = d
        return failed

    def _coerce_casts(self, fid: str, value):
        """Type a host-materialized value by the producing dissector's
        casts (LONG > DOUBLE > STRING — the reference's setter-signature
        dispatch), shared by the oracle-override path and the remapped
        sub-dissection deliveries."""
        casts = self._host_casts.get(fid)
        if casts is not None and value is not None:
            has_long, has_double = self._cast_flags.get(fid, (False, False))
            return _apply_setter_casts(value, has_long, has_double)
        return value

    @staticmethod
    def _sres_value(attr, text):
        """ScreenResolutionDissector semantics for one remapped value:
        split on the configured separator; None when absent/empty (nothing
        delivered); parts beyond the second are ignored.  The single
        implementation shared by the vectorized and per-row paths."""
        _, sep, part = attr
        if text and sep in text:
            parts = text.split(sep)
            return parts[0] if part == "width" else parts[1]
        return None

    @staticmethod
    def _last_matched_texts(m, s_row, s_vs, s_vl, buf, decoded=None):
        """Yield (row, segment text) for the LAST matched segment per row
        — the host cache-overwrite rule shared by every qscsr attr
        delivery (duplicate same-name segments dissect only the last).
        ``decoded`` = (dec_pos, darr, d_off) supplies the vector-decoded
        value for segments the flat lane already url-decoded."""
        last: Dict[int, int] = {}
        for j in m.tolist():
            last[int(s_row[j])] = j
        for row, j in last.items():
            if decoded is not None and decoded[0][j] >= 0:
                dec_pos, darr, d_off = decoded
                jj = int(dec_pos[j])
                yield row, bytes(darr[d_off[jj]:d_off[jj + 1]]).decode(
                    "latin-1"
                )
                continue
            v0 = int(s_vs[j])
            yield row, bytes(buf[row, v0 : v0 + int(s_vl[j])]).decode(
                "utf-8", "replace"
            )

    def _deliver_sres_attr(
        self, fid, p, m, s_row, s_vs, s_vl, buf, overrides, decoded=None
    ) -> None:
        """Deliver a remapped screen-resolution width/height for matched
        segments."""
        tgt = overrides[fid]
        for row, value in self._last_matched_texts(
            m, s_row, s_vs, s_vl, buf, decoded
        ):
            out = self._sres_value(p.attr, value)
            if out is not None:
                tgt[row] = self._coerce_casts(fid, out)

    @staticmethod
    def _setcookie_attr_key(fid: str, attr: str) -> str:
        """parse_attrs key for a requested attr field: the TIME.EPOCH twin
        of expires reads the millis value, everything else its own name."""
        if attr == "expires" and fid.startswith("TIME.EPOCH:"):
            return "expires_epoch"
        return attr

    def _deliver_setcookie_attr(
        self, fid, p, m, s_row, s_vs, s_vl, buf, overrides, attrs_cache
    ) -> None:
        """Deliver one per-cookie attribute field for matched segments.
        With duplicate same-name cookies, the host dissects only the LAST
        delivery (the parsable cache entry is overwritten before the
        sub-dissector consumes it), so only the last matched segment per
        row is parsed; its absent attributes read None.  ``attrs_cache``
        memoizes parse_attrs by cookie text so N requested attributes of
        one cookie split/date-parse it once."""
        from ..dissectors.cookies import ResponseSetCookieDissector

        key = self._setcookie_attr_key(fid, p.attr)
        tgt = overrides[fid]
        for row, text in self._last_matched_texts(m, s_row, s_vs, s_vl, buf):
            attrs = attrs_cache.get(text)
            if attrs is None:
                attrs = attrs_cache[text] = (
                    ResponseSetCookieDissector.parse_attrs(text)
                )
            if key in attrs:
                tgt[row] = attrs[key]

    def _materialize_csr_slow(
        self, py_rows, rows, ok, SS, NL, HE, DC, ND, VS, VL,
        uri_chain, cookie, setcookie, buf, dicts, failed,
        need_dicts, flist, overrides, columns,
    ) -> None:
        """Per-row CSR materialization for rows with segments that need
        per-value Python (url-decode, %-repair, edge trimming) — the exact
        host semantics, including decode-failure -> failed row."""
        from ..dissectors.cookies import ResponseSetCookieDissector
        from ..dissectors.utils import resilient_url_decode

        attrs_cache: Dict[str, dict] = {}
        pos_of = {int(r): j for j, r in enumerate(rows.tolist())}
        for i in py_rows.tolist():
            i = int(i)
            j = pos_of[i]
            d: Optional[Dict[str, str]] = {}
            if ok[i]:
                for k in range(SS.shape[0]):
                    nlen = int(NL[k, j])
                    has_eq = bool(HE[k, j])
                    if setcookie:
                        if not has_eq:
                            continue
                        s0 = int(SS[k, j])
                        name = (
                            bytes(buf[i, s0 : s0 + nlen])
                            .decode("utf-8", "replace")
                            .strip()
                            .lower()
                        )
                        if name == "":
                            continue
                        v0 = int(VS[k, j])
                        d[name] = bytes(
                            buf[i, v0 : v0 + int(VL[k, j])]
                        ).decode("utf-8", "replace")
                        continue
                    if nlen == 0 and not has_eq:
                        continue  # empty slot / skipped empty segment
                    s0 = int(SS[k, j])
                    name = bytes(buf[i, s0 : s0 + nlen]).decode(
                        "utf-8", "replace"
                    )
                    if uri_chain and ND[k, j]:
                        name = _fix_uri_part(name, "")
                    if cookie:
                        name = name.strip()
                    name = name.lower()
                    if name == "":
                        # "=value": the empty relative name matches
                        # neither the wildcard nor any concrete target.
                        continue
                    if not has_eq:
                        d[name] = ""
                        continue
                    v0 = int(VS[k, j])
                    value = bytes(buf[i, v0 : v0 + int(VL[k, j])]).decode(
                        "utf-8", "replace"
                    )
                    if cookie:
                        value = value.strip()
                    if DC[k, j]:
                        if uri_chain:
                            value = _fix_uri_part(value, "")
                        try:
                            value = resilient_url_decode(value)
                        except ValueError:
                            failed.add(i)
                            d = None
                            break
                    d[name] = value
            if need_dicts and d is not None:
                dicts[i] = d
            for fid, p in flist:
                if p.comp == "*":
                    continue
                if getattr(p, "attr", ""):
                    # `d` keeps the last same-name segment — exactly the
                    # one the host's cache-overwrite semantics dissect.
                    text = d.get(p.comp) if d else None
                    if isinstance(p.attr, tuple):
                        out = self._sres_value(p.attr, text)
                        if out is not None:
                            overrides[fid][i] = self._coerce_casts(fid, out)
                        continue
                    if text:
                        key = self._setcookie_attr_key(fid, p.attr)
                        attrs = attrs_cache.get(text)
                        if attrs is None:
                            attrs = attrs_cache[text] = (
                                ResponseSetCookieDissector.parse_attrs(text)
                            )
                        if key in attrs:
                            overrides[fid][i] = attrs[key]
                    continue
                overrides[fid][i] = (d.get(p.comp) if d else None)

    def _oracle_engine_tally(self) -> Optional[Dict[str, int]]:
        """Snapshot of the oracle's compiled line engine tallies (None when
        no fastline engine is active).  Used to fold per-batch DELTAS into
        the metrics registry — per-line increments stay plain ints on the
        engine; the registry is only touched at batch granularity."""
        engine = getattr(self.oracle, "_fastline", None)
        tally = getattr(engine, "tally", None)
        return dict(tally) if isinstance(tally, dict) else None

    def _fold_oracle_engine_tally(self, before: Optional[Dict[str, int]]) -> None:
        """Fold the oracle engine's tally delta since ``before`` into the
        registry as oracle_engine_lines_total{outcome=...}.  The spawn-pool
        path runs engines in child processes, so only inline-parsed lines
        are covered — the routed/rescued/rejected counters above are the
        complete view."""
        after = self._oracle_engine_tally()
        if after is None:
            return
        from ..observability import metrics

        reg = metrics()
        for outcome, n in after.items():
            delta = n - (before or {}).get(outcome, 0)
            if delta > 0:
                reg.increment("oracle_engine_lines_total", delta,
                              labels={"outcome": outcome})

    def _build_overflow_delivery(self) -> Dict[str, str]:
        """Reference Long-overflow delivery per field (values beyond
        Long.MAX_VALUE / >19-digit runs): the oracle's collecting record
        resolves AUTO setters STRING-first, so a field with a STRING
        cast stores the raw digit string — which the numeric delivery
        plan types with int() (arbitrary precision), exactly what the
        host-side overflow patch replays.  A LONG-only field stores None
        on overflow (Long.parseLong fails, the null is skip-less-
        delivered).  Anything else (DOUBLE-only) is demoted to a full
        oracle parse — exactness over speed for a class no HTTPD token
        produces.  Single source for __init__ AND __setstate__ (loaded
        pre-round-9 artifacts must classify identically)."""
        out: Dict[str, str] = {}
        for fid, c in self._host_casts.items():
            if c is not None and Cast.STRING in c:
                out[fid] = "int"
            elif c is not None and Cast.LONG in c and Cast.DOUBLE not in c:
                out[fid] = "null"
            else:
                out[fid] = "oracle"
        return out

    def _start_rescue(self, rows: List[int], lines):
        """Begin the batched host rescue for ``rows`` (sorted row ids).

        The rows' lines are framed (materialized + decoded) once up
        front; the parse goes through the oracle's batched
        ``parse_many`` (one amortized fastline-program fetch for the
        whole set) — fanned out over the spawn pool for large sets, and
        run on an assembly-pool thread when one is available so it
        overlaps the caller's CSR/column materialization.  Returns a
        collector callable yielding List[Optional[values-dict]] in row
        order."""
        if not rows:
            return lambda: []
        batch_lines = [lines[i] for i in rows]
        pool = self.assembly_pool()
        if pool.workers > 1:
            fut = pool.submit(lambda: self._run_oracle_many(batch_lines))
            if fut is not None:
                return fut.result
        return lambda: self._run_oracle_many(batch_lines)

    def _run_oracle(self, line: Union[bytes, str]) -> Optional[Dict[str, Any]]:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="replace")
        try:
            record = self.oracle.parse(line, _CollectingRecord())
        except DissectionFailure:
            return None
        return record.values

    # Fallback sets at least this large fan out over the process pool;
    # smaller ones run inline (pool startup is ~seconds once per parser).
    oracle_parallel_threshold = 512

    def _oracle_pool_get(self):
        if getattr(self, "_oracle_pool", None) is None:
            import multiprocessing as mp
            import pickle

            n = min(8, os.cpu_count() or 1)
            if n < 2 or os.environ.get("LOGPARSER_TPU_ORACLE_PROCS") == "0":
                self._oracle_pool = False
            else:
                # The workers run the pure-Python oracle only: scrub
                # accelerator bootstrap variables from the child env so
                # site hooks don't drag a device runtime (and possibly a
                # device-attachment handshake) into every worker.
                scrub = ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")
                saved = {v: os.environ.pop(v) for v in scrub if v in os.environ}
                os.environ["JAX_PLATFORMS"] = "cpu"
                try:
                    ctx = mp.get_context("spawn")
                    pool = ctx.Pool(
                        n,
                        initializer=_oracle_worker_init,
                        initargs=(pickle.dumps(self.oracle),),
                    )
                    # Readiness probe: a child-side initializer failure
                    # (e.g. the oracle references a __main__-defined
                    # dissector the spawn child cannot import) makes Pool
                    # respawn dying workers forever and map() would hang —
                    # probe with a timeout and fall back inline instead.
                    try:
                        pool.apply_async(_oracle_worker_run, ([],)).get(
                            timeout=120
                        )
                    except Exception:
                        pool.terminate()
                        pool.join()
                        raise
                    self._oracle_pool = pool
                    self._oracle_pool_n = n
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "oracle worker pool unavailable; falling back to "
                        "inline parsing", exc_info=True,
                    )
                    self._oracle_pool = False
                finally:
                    os.environ.pop("JAX_PLATFORMS", None)
                    os.environ.update(saved)
        return self._oracle_pool or None

    def _run_oracle_many(
        self, lines: List[Union[bytes, str]]
    ) -> List[Optional[Dict[str, Any]]]:
        """Oracle-parse many lines, fanning out over the worker pool when
        the set is large enough to amortize IPC.  The inline path uses
        the oracle's batched ``parse_many`` (one amortized fastline
        program fetch for the whole rescue set)."""
        decoded = [
            ln.decode("utf-8", errors="replace") if isinstance(ln, bytes) else ln
            for ln in lines
        ]
        pool = (
            self._oracle_pool_get()
            if len(decoded) >= self.oracle_parallel_threshold
            else None
        )
        if pool is None:
            return [
                _values_of(rec)
                for rec in self.oracle.parse_many(decoded, _CollectingRecord)
            ]
        n_chunks = self._oracle_pool_n * 4
        size = max(1, (len(decoded) + n_chunks - 1) // n_chunks)
        chunks = [decoded[i : i + size] for i in range(0, len(decoded), size)]
        out: List[Optional[Dict[str, Any]]] = []
        for part in pool.map(_oracle_worker_run, chunks):
            out.extend(part)
        return out

    def close(self) -> None:
        """Release the fallback worker pool (if one was started) and the
        Arrow assembly thread pool."""
        pool = getattr(self, "_oracle_pool", None)
        if pool:
            pool.terminate()
            pool.join()
        self._oracle_pool = None
        apool = getattr(self, "_assembly_pool", None)
        if apool is not None:
            apool.close()
        self._assembly_pool = None

    # ------------------------------------------------------------------
    # serialization — the compiled format program (token tables, split ops,
    # packed layouts, field plans) is a serializable, device-loadable
    # artifact.  The analogue of the reference's `Parser implements
    # Serializable` contract (Parser.java:91-97): engines serialize the
    # parser once and ship it to workers; jit executables are rebuilt on
    # load the way the reference re-resolves reflection Methods.
    #
    # SECURITY: the payload is a pickle (exactly as the reference's artifact
    # is a Java serialized object) — loading executes code from the blob.
    # Only load artifacts produced by your own pipeline over a trusted
    # channel; never feed user-uploaded files to from_bytes/load.
    # ------------------------------------------------------------------

    _ARTIFACT_MAGIC = b"LPTPU-PROGRAM-v1\n"
    # v2 wraps the v1 parser pickle with serialized AOT executables for
    # the shapes this process compiled (docs/COMPILE.md "Artifact
    # layout"): a fresh host loading the artifact executes its first
    # batch without lowering anything.  v1 artifacts stay loadable.
    _ARTIFACT_MAGIC_V2 = b"LPTPU-PROGRAM-v2\n"

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["_jitted"] = None
        state["_jitted_views"] = None
        state["_oracle_pool"] = None  # worker pools never ship in artifacts
        state["_assembly_pool"] = None  # rebuilt lazily from the knob
        # Device handles never ship: the mesh is re-resolved on the
        # LOADING host from the pickled data_parallel request (a
        # different host may have a different chip count).
        state["_mesh"] = None
        # Runtime fault state never ships either: a breaker/clamp
        # learned on one host's devices means nothing on another's, and
        # chaos re-arms from the loading process's env.
        state["_breaker"] = None
        state["_device_chaos"] = None
        state["_oom_clamp"] = None
        state["_oom_events"] = 0
        # Aggregate executors are jit handles (rebuilt lazily on load);
        # the compile-demote set is runtime fault state like the breaker.
        state["_agg_fns"] = {}
        state["_agg_disabled"] = set()
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        # Legacy artifact keys (use_pallas/_pallas_fns from pre-round-3
        # builds, when an experimental Pallas executor existed) are
        # dropped on load.
        for legacy in ("_pallas_fns", "use_pallas", "_use_pallas_explicit"):
            state.pop(legacy, None)
        self.__dict__.update(state)
        if "csr_slots" not in state:  # pre-adaptive-CSR artifacts
            from .pipeline import CSR_SLOTS

            self.csr_slots = CSR_SLOTS
        if "_device_covers_all_formats" not in state:  # pre-filter artifacts
            self._device_covers_all_formats = False  # conservatively off
        if "_cast_flags" not in state:  # pre-round-5 artifacts
            self._cast_flags = {
                f: (Cast.LONG in c, Cast.DOUBLE in c)
                for f, c in self._host_casts.items()
                if c is not None
            }
        if "_view_demand" not in state:  # pre-round-6 artifacts
            self._view_demand = None
        if "assembly_workers" not in state:
            self.assembly_workers = None
        if "_overflow_delivery" not in state:  # pre-round-9 artifacts
            self._overflow_delivery = self._build_overflow_delivery()
        if "data_parallel" not in state:  # pre-pod artifacts
            self.data_parallel = None
        if "_agg_fns" not in state:  # pre-analytics artifacts
            self._agg_fns = {}
            self._agg_disabled = set()
        # Fault layer rebuilds fresh on the loading host: pickled knobs
        # (budget/deadline/policy) are honored, env fallbacks re-read,
        # breaker/clamp/chaos start clean (pre-fault-layer artifacts
        # get the defaults).
        self._init_fault_layer(
            state.get("device_bytes_budget"),
            state.get("execute_deadline_s"),
            state.get("fault_policy"),
            "env",
        )
        # Re-resolve the mesh on THIS host (never pickled; the loading
        # host's device count decides the effective width).
        self._mesh = self._build_mesh(self.data_parallel)
        # Pre-widening artifacts packed 18-digit limb layouts (no d18/big
        # aux slots).  Layouts are deterministic functions of the plans +
        # slot count, so rebuild them to the current frame format.
        needs_layout = any(
            p.kind in ("long", "secmillis") and "big" not in u.layout.slots.get(
                p.field_id, {"big": None}
            )
            for u in self.units for p in u.plans
        )
        if needs_layout:
            for u in self.units:
                u.layout = PackedLayout.for_plans(u.plans, self.csr_slots)
            assign_row_offsets(self.units)
        self._assembly_pool = None
        self._jitted = self._build_jitted()
        self._jitted_views = None

    def to_bytes(self, embed_executables: bool = True) -> bytes:
        """The compiled parser as a versioned artifact blob (a pickle — see
        the SECURITY note above: treat artifacts as executable).

        ``embed_executables`` (default) also ships the serialized AOT
        executables for every shape bucket this process has compiled or
        loaded — warm the ladder first (:meth:`prewarm`) to mint an
        artifact whose loading host never lowers anything.  A parser with
        nothing compiled yet (or a mesh-sharded executor, whose
        executables bind this process's device set) emits a plain v1
        blob."""
        import pickle

        execs = self._export_executables() if embed_executables else []
        if not execs:
            return self._ARTIFACT_MAGIC + pickle.dumps(self)
        from .compile_cache import backend_fingerprint

        return self._ARTIFACT_MAGIC_V2 + pickle.dumps({
            "parser": self,
            "backend": backend_fingerprint(),
            "execs": execs,
        })

    def _export_executables(self) -> List[Dict[str, Any]]:
        from .compile_cache import AotExecutor

        out: List[Dict[str, Any]] = []
        seen = set()
        for tag, fn in (("plain", self._jitted),
                        ("views", self._jitted_views)):
            if (not isinstance(fn, AotExecutor) or not fn.serializable
                    or id(fn) in seen):
                continue
            seen.add(id(fn))
            for (b, l), payload in fn.export_payloads().items():
                out.append({
                    "tag": tag, "b": b, "l": l, "payload": payload,
                    "fingerprint": fn.fingerprint,
                })
        return out

    def _preload_executables(self, execs: List[Dict[str, Any]],
                             backend: Optional[str]) -> int:
        """Install artifact-embedded executables into the rebuilt AOT
        executors.  Fingerprint or backend drift refuses the entry (the
        shape compiles fresh on first use — never a wrong kernel);
        returns how many shapes went live."""
        from ..observability import log_warning_once, metrics
        from .compile_cache import AotExecutor

        loaded = 0
        for e in execs:
            fn = (self._jitted if e.get("tag") == "plain"
                  else self.device_views_fn())
            if not isinstance(fn, AotExecutor):
                continue
            if e.get("fingerprint") != fn.fingerprint:
                metrics().increment("compile_cache_errors_total",
                                    labels={"kind": "fingerprint"})
                log_warning_once(
                    _LOG,
                    "artifact executable refused (fingerprint drift); "
                    "recompiling fresh",
                )
                continue
            if fn.preload(int(e["b"]), int(e["l"]), e["payload"], backend):
                loaded += 1
        return loaded

    @classmethod
    def from_bytes(cls, blob: bytes) -> "TpuBatchParser":
        """Load an artifact produced by :meth:`to_bytes`.  TRUSTED INPUT
        ONLY — the payload is a pickle and loading executes code."""
        import pickle

        if blob.startswith(cls._ARTIFACT_MAGIC_V2):
            d = pickle.loads(blob[len(cls._ARTIFACT_MAGIC_V2):])
            parser = d.get("parser") if isinstance(d, dict) else None
            if not isinstance(parser, cls):
                raise ValueError("artifact does not contain a TpuBatchParser")
            parser._preload_executables(
                d.get("execs") or [], d.get("backend")
            )
            return parser
        if not blob.startswith(cls._ARTIFACT_MAGIC):
            raise ValueError("not a logparser_tpu program artifact")
        parser = pickle.loads(blob[len(cls._ARTIFACT_MAGIC):])
        if not isinstance(parser, cls):
            raise ValueError("artifact does not contain a TpuBatchParser")
        return parser

    def save(self, path: str, embed_executables: bool = True) -> None:
        with open(path, "wb") as f:
            f.write(self.to_bytes(embed_executables))

    @classmethod
    def load(cls, path: str) -> "TpuBatchParser":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())
