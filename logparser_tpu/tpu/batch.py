"""The batch parsing API: ``TpuBatchParser.parse_batch(lines) -> BatchResult``.

This is the product hot path (SURVEY §7: "compile the LogFormat to a static
field-extraction program, execute it over [B, L] uint8 batches on TPU").
Strings never leave the device as Python strings: string-typed fields are
(offset, length) span columns into the input buffer; numeric/epoch fields are
int32-limb columns decoded on device and combined to int64 on the host.

The split program AND all requested post-stages (numeric parse, timestamp ->
epoch, first-line split) trace into ONE jitted function per parser — a single
fused XLA computation per (B, L) shape bucket; batch and line length are both
padded to power-of-two buckets so recompilation is bounded.

The host oracle (the exact per-line engine in logparser_tpu.core/httpd)
handles lines the optimistic device split rejects (including multi-format
switching) and requested fields outside the device-resolvable set (wildcards,
URI repair, cookies, ...), so the combined result is bit-exact with the
reference semantics at batch throughput for the common case.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core.exceptions import DissectionFailure
from ..core.fields import cleanup_field_value
from ..httpd.parser import HttpdLoglineParser
from .program import (
    CS_CLF_DIGITS,
    CS_DIGITS,
    DeviceProgram,
    UnsupportedFormatError,
    compile_device_program,
)
from .runtime import _run_program_impl, encode_batch
from . import postproc

_NUMERIC_KINDS = {"long", "long_clf_null", "long_clf_zero", "epoch"}


@dataclass
class _FieldPlan:
    field_id: str                 # cleaned "TYPE:path"
    kind: str                     # span | long | long_clf_null | long_clf_zero
    #                             | epoch | fl_method | fl_uri | fl_protocol | host
    token_index: int = -1


class _CollectingRecord:
    """Host-fallback record capturing every delivered value by field id."""

    def __init__(self) -> None:
        self.values: Dict[str, Any] = {}

    def set_value(self, name: str, value) -> None:
        self.values[name] = value


class BatchResult:
    """Columnar parse result over one batch."""

    def __init__(self, lines, buf, lengths, valid, columns, overrides, good, bad):
        self._lines = lines
        self.buf = buf                  # np [B, L] uint8
        self.lengths = lengths
        self.valid = valid              # np [B] bool: overall line validity
        self._columns = columns         # field_id -> dict of arrays (per kind)
        self._overrides = overrides     # field_id -> {row: python value}
        self.lines_read = len(lines)
        self.good_lines = good
        self.bad_lines = bad

    def field_ids(self) -> List[str]:
        return list(self._columns.keys())

    def column(self, field_id: str) -> Dict[str, np.ndarray]:
        """Raw column arrays: spans have starts/ends; numerics have values +
        null mask."""
        return self._columns[cleanup_field_value(field_id)]

    def to_pylist(self, field_id: str) -> List[Any]:
        """Materialize one column as Python values (strings/ints/None)."""
        field_id = cleanup_field_value(field_id)
        col = self._columns[field_id]
        overrides = self._overrides.get(field_id, {})
        out: List[Any] = []
        kind = col["kind"]
        for i in range(self.lines_read):
            if i in overrides:
                out.append(overrides[i])
                continue
            if not self.valid[i] or not col["ok"][i]:
                out.append(None)
                continue
            if kind in _NUMERIC_KINDS:
                if col["null"][i]:
                    out.append(0 if kind == "long_clf_zero" else None)
                else:
                    out.append(int(col["values"][i]))
            else:
                start, end = int(col["starts"][i]), int(col["ends"][i])
                raw = bytes(self.buf[i, start:end])
                if raw == b"-":
                    out.append(None)  # decode_extracted_value: '-' -> null
                else:
                    out.append(raw.decode("utf-8", errors="replace"))
        return out

    def to_dict(self) -> Dict[str, List[Any]]:
        return {fid: self.to_pylist(fid) for fid in self._columns}

    def to_arrow(self, include_validity: bool = True):
        """Materialize as a pyarrow.Table (see tpu/arrow_bridge.py)."""
        from .arrow_bridge import batch_to_arrow

        return batch_to_arrow(self, include_validity=include_validity)


def _bucket_batch(b: int, minimum: int = 64) -> int:
    size = minimum
    while size < b:
        size *= 2
    return size


class TpuBatchParser:
    """Compiles one LogFormat + requested fields into a fused device function
    and a host-fallback parser."""

    def __init__(
        self,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
        type_remappings: Optional[Dict[str, Any]] = None,
        extra_dissectors: Optional[Sequence[Any]] = None,
    ):
        self.log_format = log_format
        self.requested = [cleanup_field_value(f) for f in fields]

        # Host oracle parser (also the metadata source).
        self.oracle = HttpdLoglineParser(_CollectingRecord, log_format, timestamp_format)
        self.oracle.apply_config(type_remappings, extra_dissectors)
        self.oracle.add_parse_target("set_value", list(self.requested))
        self.oracle.assemble_dissectors()

        # Device program for the FIRST registered format; other formats are
        # host-fallback territory (multi-format batches run the switch logic
        # per invalid line).
        fmt = self.oracle.all_dissectors[0]
        dissectors = getattr(fmt, "dissectors", [fmt])
        self.program: Optional[DeviceProgram]
        try:
            self.program = compile_device_program(dissectors[0])
        except UnsupportedFormatError:
            self.program = None

        self.plans: List[_FieldPlan] = [self._resolve(fid) for fid in self.requested]
        self.plan_by_id = {p.field_id: p for p in self.plans}
        self.host_fields = [p.field_id for p in self.plans if p.kind == "host"]
        self._host_casts = {
            fid: self.oracle.get_casts(fid) for fid in self.host_fields
        }
        # No point running the device program when every field is host-only.
        any_device_field = any(p.kind != "host" for p in self.plans)
        self._jitted = (
            jax.jit(self._device_fn)
            if self.program is not None and any_device_field
            else None
        )

    # ------------------------------------------------------------------

    def _resolve(self, field_id: str) -> _FieldPlan:
        if self.program is None:
            return _FieldPlan(field_id, "host")
        ftype, _, path = field_id.partition(":")
        for tok in self.program.tokens:
            for out_type, out_name in tok.outputs:
                if out_name == path:
                    if out_type == ftype:
                        if tok.charset == CS_DIGITS:
                            return _FieldPlan(field_id, "long", tok.index)
                        if tok.charset == CS_CLF_DIGITS:
                            return _FieldPlan(field_id, "long_clf_null", tok.index)
                        return _FieldPlan(field_id, "span", tok.index)
                    # CLF -> number translator edge (BYTESCLF token, BYTES asked)
                    if out_type == "BYTESCLF" and ftype == "BYTES":
                        return _FieldPlan(field_id, "long_clf_zero", tok.index)
                elif path.startswith(out_name + "."):
                    suffix = path[len(out_name) + 1 :]
                    if out_type == "TIME.STAMP" and ftype == "TIME.EPOCH" and suffix == "epoch":
                        return _FieldPlan(field_id, "epoch", tok.index)
                    if out_type == "HTTP.FIRSTLINE":
                        if ftype == "HTTP.METHOD" and suffix == "method":
                            return _FieldPlan(field_id, "fl_method", tok.index)
                        if ftype == "HTTP.URI" and suffix == "uri":
                            return _FieldPlan(field_id, "fl_uri", tok.index)
                        if ftype == "HTTP.PROTOCOL_VERSION" and suffix == "protocol":
                            return _FieldPlan(field_id, "fl_protocol", tok.index)
        return _FieldPlan(field_id, "host")

    # ------------------------------------------------------------------
    # The fused device computation (traced once per input shape).
    # ------------------------------------------------------------------

    def _device_fn(self, buf: jnp.ndarray, lengths: jnp.ndarray):
        res = _run_program_impl(self.program, buf, lengths)
        starts, ends, valid = res["starts"], res["ends"], res["valid"]

        fl_cache: Dict[int, Dict[str, jnp.ndarray]] = {}
        cols: Dict[str, Any] = {}
        for plan in self.plans:
            if plan.kind in ("host", "span"):
                continue
            t_start = starts[plan.token_index]
            t_end = ends[plan.token_index]
            if plan.kind in ("long", "long_clf_null", "long_clf_zero"):
                limbs, is_null, ok = postproc.parse_long_spans(
                    buf, t_start, t_end, clf=plan.kind != "long"
                )
                cols[plan.field_id] = (limbs, is_null, ok)
            elif plan.kind == "epoch":
                parts, ok = postproc.parse_apache_timestamp(buf, t_start, t_end)
                cols[plan.field_id] = (parts, ok)
                # A timestamp the host layout rejects raises DissectionFailure
                # there, failing the whole line — mirror that: route the line
                # to the oracle (which will reject it identically).
                valid = valid & ok
            elif plan.kind in ("fl_method", "fl_uri", "fl_protocol"):
                if plan.token_index not in fl_cache:
                    fl_cache[plan.token_index] = postproc.split_firstline(
                        buf, lengths, t_start, t_end
                    )
                fl = fl_cache[plan.token_index]
                part = plan.kind[3:]
                if part == "protocol":
                    ok = fl["ok"] & fl["has_protocol"]
                    s, e = fl["proto_start"], fl["proto_end"]
                else:
                    ok = fl["ok"]
                    s, e = fl[f"{part}_start"], fl[f"{part}_end"]
                cols[plan.field_id] = (s, e, ok)
        return {"valid": valid, "starts": starts, "ends": ends, "cols": cols}

    # ------------------------------------------------------------------

    def parse_batch(self, lines: Sequence[Union[bytes, str]]) -> BatchResult:
        B = len(lines)
        buf, lengths, overflow = encode_batch(lines)
        # Pad the batch dimension to a bucket so jit recompiles stay bounded.
        padded_b = _bucket_batch(B)
        if padded_b != B:
            buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
            lengths = np.pad(lengths, (0, padded_b - B))

        columns: Dict[str, Dict[str, np.ndarray]] = {}
        ones = np.ones(B, dtype=bool)
        zeros_null = np.zeros(B, dtype=bool)

        if self._jitted is not None:
            dev = self._jitted(jnp.asarray(buf), jnp.asarray(lengths))
            dev = jax.device_get(dev)
            valid = np.array(dev["valid"][:B])
            starts = dev["starts"][:, :B]
            ends = dev["ends"][:, :B]
            dev_cols = dev["cols"]
        else:
            valid = np.zeros(B, dtype=bool)
            starts = ends = np.zeros((1, B), dtype=np.int32)
            dev_cols = {}
        for i in overflow:
            valid[i] = False

        for plan in self.plans:
            if plan.kind == "host":
                columns[plan.field_id] = {
                    "kind": "span",
                    "starts": np.zeros(B, dtype=np.int32),
                    "ends": np.zeros(B, dtype=np.int32),
                    "ok": np.zeros(B, dtype=bool),
                    "null": zeros_null,
                }
            elif plan.kind == "span":
                columns[plan.field_id] = {
                    "kind": "span",
                    "starts": starts[plan.token_index],
                    "ends": ends[plan.token_index],
                    "ok": ones,
                    "null": zeros_null,
                }
            else:
                packed = dev_cols[plan.field_id]
                if plan.kind in ("long", "long_clf_null", "long_clf_zero"):
                    (hi, lo, lo_digits), is_null, ok = packed
                    is_null = np.asarray(is_null)[:B]
                    columns[plan.field_id] = {
                        "kind": plan.kind,
                        "values": postproc.combine_long_limbs(
                            hi[:B], lo[:B], lo_digits[:B], is_null
                        ),
                        "null": is_null,
                        "ok": np.asarray(ok)[:B],
                    }
                elif plan.kind == "epoch":
                    (days, sec_of_day), ok = packed
                    columns[plan.field_id] = {
                        "kind": "epoch",
                        "values": postproc.combine_epoch(days[:B], sec_of_day[:B]),
                        "null": zeros_null,
                        "ok": np.asarray(ok)[:B],
                    }
                else:  # span (firstline parts)
                    s, e, ok = packed
                    columns[plan.field_id] = {
                        "kind": "span",
                        "starts": np.asarray(s)[:B],
                        "ends": np.asarray(e)[:B],
                        "ok": np.asarray(ok)[:B],
                        "null": zeros_null,
                    }

        # Host fallback: invalid lines entirely; host-only fields for every line.
        def coerce(fid: str, value: Any) -> Any:
            if value is None:
                return None
            if self.plan_by_id[fid].kind in _NUMERIC_KINDS:
                try:
                    return int(value)
                except (TypeError, ValueError):
                    return None
            # Host-delivered values arrive as oracle strings; deliver them
            # typed per the producing dissector's casts (LONG > DOUBLE >
            # STRING, matching the reference's setter-signature dispatch).
            casts = self._host_casts.get(fid)
            if casts is not None:
                from ..core.casts import Cast

                if Cast.LONG in casts:
                    try:
                        return int(value)
                    except (TypeError, ValueError):
                        pass
                if Cast.DOUBLE in casts:
                    try:
                        return float(value)
                    except (TypeError, ValueError):
                        pass
            return value

        overrides: Dict[str, Dict[int, Any]] = {fid: {} for fid in columns}
        bad = 0
        invalid_rows = set(int(i) for i in np.nonzero(~valid)[0])
        host_rows = range(B) if self.host_fields else sorted(invalid_rows)
        for i in host_rows:
            is_invalid = i in invalid_rows
            fields_needed = self.requested if is_invalid else self.host_fields
            values = self._run_oracle(lines[i])
            if values is None:
                if is_invalid:
                    bad += 1
                continue
            if is_invalid:
                valid[i] = True
            for fid in fields_needed:
                if fid.endswith(".*"):
                    # Wildcard target: deliver {relative.name: value} built
                    # from every concrete field under the prefix (the oracle
                    # stores them under their full TYPE:path names).
                    prefix = fid[:-1]  # keep the trailing dot
                    overrides[fid][i] = {
                        k[len(prefix):]: v
                        for k, v in values.items()
                        if k.startswith(prefix)
                    }
                else:
                    overrides[fid][i] = coerce(fid, values.get(fid))

        good = int(B - bad)
        return BatchResult(
            list(lines), buf[:B], lengths[:B], valid, columns, overrides, good, bad
        )

    def _run_oracle(self, line: Union[bytes, str]) -> Optional[Dict[str, Any]]:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="replace")
        try:
            record = self.oracle.parse(line, _CollectingRecord())
        except DissectionFailure:
            return None
        return record.values
