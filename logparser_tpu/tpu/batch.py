"""The batch parsing API: ``TpuBatchParser.parse_batch(lines) -> BatchResult``.

This is the product hot path (SURVEY §7: "compile the LogFormat to a static
field-extraction program, execute it over [B, L] uint8 batches on TPU").
Strings never leave the device as Python strings: string-typed fields are
(offset, length) span columns into the input buffer; numeric/epoch fields are
int32-limb columns decoded on device and combined to int64 on the host.

The split program AND all requested post-stages (numeric parse, timestamp ->
epoch, first-line split) trace into ONE jitted function per parser — a single
fused XLA computation per (B, L) shape bucket; batch and line length are both
padded to power-of-two buckets so recompilation is bounded.

The host oracle (the exact per-line engine in logparser_tpu.core/httpd)
handles lines the optimistic device split rejects (including multi-format
switching) and requested fields outside the device-resolvable set (wildcards,
URI repair, cookies, ...), so the combined result is bit-exact with the
reference semantics at batch throughput for the common case.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

import os

from ..core.exceptions import DissectionFailure
from ..core.fields import cleanup_field_value
from ..httpd.parser import HttpdLoglineParser
from .pipeline import (
    FieldPlan,
    PackedLayout,
    build_jnp_fn,
    build_pallas_fn,
)
from .program import (
    CS_CLF_DIGITS,
    CS_DIGITS,
    DeviceProgram,
    UnsupportedFormatError,
    compile_device_program,
)
from .runtime import encode_batch
from . import postproc

_NUMERIC_KINDS = {"long", "long_clf_null", "long_clf_zero", "epoch"}

# Back-compat alias (plan resolution lives here; packing in pipeline.py).
_FieldPlan = FieldPlan


def _default_use_pallas() -> bool:
    env = os.environ.get("LOGPARSER_TPU_PALLAS")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no")
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


class _CollectingRecord:
    """Host-fallback record capturing every delivered value by field id."""

    def __init__(self) -> None:
        self.values: Dict[str, Any] = {}

    def set_value(self, name: str, value) -> None:
        self.values[name] = value


class BatchResult:
    """Columnar parse result over one batch."""

    def __init__(self, lines, buf, lengths, valid, columns, overrides, good, bad):
        self._lines = lines
        self.buf = buf                  # np [B, L] uint8
        self.lengths = lengths
        self.valid = valid              # np [B] bool: overall line validity
        self._columns = columns         # field_id -> dict of arrays (per kind)
        self._overrides = overrides     # field_id -> {row: python value}
        self.lines_read = len(lines)
        self.good_lines = good
        self.bad_lines = bad

    def field_ids(self) -> List[str]:
        return list(self._columns.keys())

    def column(self, field_id: str) -> Dict[str, np.ndarray]:
        """Raw column arrays: spans have starts/ends; numerics have values +
        null mask."""
        return self._columns[cleanup_field_value(field_id)]

    def to_pylist(self, field_id: str) -> List[Any]:
        """Materialize one column as Python values (strings/ints/None)."""
        field_id = cleanup_field_value(field_id)
        col = self._columns[field_id]
        overrides = self._overrides.get(field_id, {})
        out: List[Any] = []
        kind = col["kind"]
        for i in range(self.lines_read):
            if i in overrides:
                out.append(overrides[i])
                continue
            if not self.valid[i] or not col["ok"][i]:
                out.append(None)
                continue
            if kind in _NUMERIC_KINDS:
                if col["null"][i]:
                    out.append(0 if kind == "long_clf_zero" else None)
                else:
                    out.append(int(col["values"][i]))
            else:
                start, end = int(col["starts"][i]), int(col["ends"][i])
                raw = bytes(self.buf[i, start:end])
                if raw == b"-":
                    out.append(None)  # decode_extracted_value: '-' -> null
                else:
                    out.append(raw.decode("utf-8", errors="replace"))
        return out

    def to_dict(self) -> Dict[str, List[Any]]:
        return {fid: self.to_pylist(fid) for fid in self._columns}

    def to_arrow(self, include_validity: bool = True):
        """Materialize as a pyarrow.Table (see tpu/arrow_bridge.py)."""
        from .arrow_bridge import batch_to_arrow

        return batch_to_arrow(self, include_validity=include_validity)


def _bucket_batch(b: int, minimum: int = 64) -> int:
    size = minimum
    while size < b:
        size *= 2
    return size


class TpuBatchParser:
    """Compiles one LogFormat + requested fields into a fused device function
    and a host-fallback parser."""

    def __init__(
        self,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
        type_remappings: Optional[Dict[str, Any]] = None,
        extra_dissectors: Optional[Sequence[Any]] = None,
        use_pallas: Optional[bool] = None,
    ):
        self.log_format = log_format
        self.requested = [cleanup_field_value(f) for f in fields]
        self.use_pallas = (
            _default_use_pallas() if use_pallas is None else use_pallas
        )

        # Host oracle parser (also the metadata source).
        self.oracle = HttpdLoglineParser(_CollectingRecord, log_format, timestamp_format)
        self.oracle.apply_config(type_remappings, extra_dissectors)
        self.oracle.add_parse_target("set_value", list(self.requested))
        self.oracle.assemble_dissectors()

        # Device program for the FIRST registered format; other formats are
        # host-fallback territory (multi-format batches run the switch logic
        # per invalid line).
        fmt = self.oracle.all_dissectors[0]
        dissectors = getattr(fmt, "dissectors", [fmt])
        self.program: Optional[DeviceProgram]
        try:
            self.program = compile_device_program(dissectors[0])
        except UnsupportedFormatError:
            self.program = None

        self.plans: List[_FieldPlan] = [self._resolve(fid) for fid in self.requested]
        self.plan_by_id = {p.field_id: p for p in self.plans}
        self.host_fields = [p.field_id for p in self.plans if p.kind == "host"]
        self._host_casts = {
            fid: self.oracle.get_casts(fid) for fid in self.host_fields
        }
        # No point running the device program when every field is host-only.
        any_device_field = any(p.kind != "host" for p in self.plans)
        self.layout = PackedLayout.for_plans(self.plans)
        if self.program is not None and any_device_field:
            self._jitted = build_jnp_fn(self.program, self.plans, self.layout)
        else:
            self._jitted = None
        self._pallas_fns: Dict[tuple, Any] = {}  # (B, L) -> jitted pallas fn

    def device_fn(self, B: int, L: int):
        """The fused device executor for one [B, L] shape bucket: Pallas on
        TPU (one VMEM-resident kernel), plain XLA elsewhere."""
        if self._jitted is None:
            return None
        if not self.use_pallas:
            return self._jitted
        key = (B, L)
        fn = self._pallas_fns.get(key)
        if fn is None:
            fn = build_pallas_fn(self.program, self.plans, self.layout, B, L)
            self._pallas_fns[key] = fn
        return fn

    # ------------------------------------------------------------------

    def _resolve(self, field_id: str) -> _FieldPlan:
        if self.program is None:
            return _FieldPlan(field_id, "host")
        ftype, _, path = field_id.partition(":")
        for tok in self.program.tokens:
            for out_type, out_name in tok.outputs:
                if out_name == path:
                    if out_type == ftype:
                        if tok.charset == CS_DIGITS:
                            return _FieldPlan(field_id, "long", tok.index)
                        if tok.charset == CS_CLF_DIGITS:
                            return _FieldPlan(field_id, "long_clf_null", tok.index)
                        return _FieldPlan(field_id, "span", tok.index)
                    # CLF -> number translator edge (BYTESCLF token, BYTES asked)
                    if out_type == "BYTESCLF" and ftype == "BYTES":
                        return _FieldPlan(field_id, "long_clf_zero", tok.index)
                elif path.startswith(out_name + "."):
                    suffix = path[len(out_name) + 1 :]
                    if out_type == "TIME.STAMP" and ftype == "TIME.EPOCH" and suffix == "epoch":
                        return _FieldPlan(field_id, "epoch", tok.index)
                    if out_type == "HTTP.FIRSTLINE":
                        if ftype == "HTTP.METHOD" and suffix == "method":
                            return _FieldPlan(field_id, "fl_method", tok.index)
                        if ftype == "HTTP.URI" and suffix == "uri":
                            return _FieldPlan(field_id, "fl_uri", tok.index)
                        if ftype == "HTTP.PROTOCOL_VERSION" and suffix == "protocol":
                            return _FieldPlan(field_id, "fl_protocol", tok.index)
        return _FieldPlan(field_id, "host")

    # ------------------------------------------------------------------

    def parse_batch(self, lines: Sequence[Union[bytes, str]]) -> BatchResult:
        B = len(lines)
        buf, lengths, overflow = encode_batch(lines)
        # Pad the batch dimension to a bucket so jit recompiles stay bounded.
        padded_b = _bucket_batch(B)
        if padded_b != B:
            buf = np.pad(buf, ((0, padded_b - B), (0, 0)))
            lengths = np.pad(lengths, (0, padded_b - B))

        columns: Dict[str, Dict[str, np.ndarray]] = {}
        ones = np.ones(B, dtype=bool)
        zeros_null = np.zeros(B, dtype=bool)

        fn = self.device_fn(padded_b, buf.shape[1])
        if fn is not None:
            # ONE packed [K, B] int32 output -> ONE device->host fetch
            # (transfer round-trips dominate on tunneled TPU attachments).
            packed = np.asarray(
                jax.device_get(fn(jnp.asarray(buf), jnp.asarray(lengths)))
            )
            valid = packed[0, :B] != 0
        else:
            packed = None
            valid = np.zeros(B, dtype=bool)
        for i in overflow:
            valid[i] = False

        get = (
            (lambda fid, comp: self.layout.get(packed, fid, comp)[:B])
            if packed is not None
            else None
        )
        for plan in self.plans:
            if plan.kind == "host" or packed is None:
                columns[plan.field_id] = {
                    "kind": "span",
                    "starts": np.zeros(B, dtype=np.int32),
                    "ends": np.zeros(B, dtype=np.int32),
                    "ok": np.zeros(B, dtype=bool),
                    "null": zeros_null,
                }
            elif plan.kind in ("span", "fl_method", "fl_uri", "fl_protocol"):
                starts_col = get(plan.field_id, "start")
                columns[plan.field_id] = {
                    "kind": "span",
                    "starts": starts_col,
                    "ends": starts_col + get(plan.field_id, "len"),
                    "ok": get(plan.field_id, "ok") != 0,
                    "null": zeros_null,
                }
            elif plan.kind in ("long", "long_clf_null", "long_clf_zero"):
                is_null = get(plan.field_id, "null") != 0
                columns[plan.field_id] = {
                    "kind": plan.kind,
                    "values": postproc.combine_long_limbs(
                        get(plan.field_id, "hi"),
                        get(plan.field_id, "lo"),
                        get(plan.field_id, "lo_digits"),
                        is_null,
                    ),
                    "null": is_null,
                    "ok": get(plan.field_id, "ok") != 0,
                }
            else:  # epoch
                columns[plan.field_id] = {
                    "kind": "epoch",
                    "values": postproc.combine_epoch(
                        get(plan.field_id, "days"), get(plan.field_id, "sec")
                    ),
                    "null": zeros_null,
                    "ok": get(plan.field_id, "ok") != 0,
                }

        # Host fallback: invalid lines entirely; host-only fields for every line.
        def coerce(fid: str, value: Any) -> Any:
            if value is None:
                return None
            if self.plan_by_id[fid].kind in _NUMERIC_KINDS:
                try:
                    return int(value)
                except (TypeError, ValueError):
                    return None
            # Host-delivered values arrive as oracle strings; deliver them
            # typed per the producing dissector's casts (LONG > DOUBLE >
            # STRING, matching the reference's setter-signature dispatch).
            casts = self._host_casts.get(fid)
            if casts is not None:
                from ..core.casts import Cast

                if Cast.LONG in casts:
                    try:
                        return int(value)
                    except (TypeError, ValueError):
                        pass
                if Cast.DOUBLE in casts:
                    try:
                        return float(value)
                    except (TypeError, ValueError):
                        pass
            return value

        overrides: Dict[str, Dict[int, Any]] = {fid: {} for fid in columns}
        bad = 0
        invalid_rows = set(int(i) for i in np.nonzero(~valid)[0])
        host_rows = range(B) if self.host_fields else sorted(invalid_rows)
        for i in host_rows:
            is_invalid = i in invalid_rows
            fields_needed = self.requested if is_invalid else self.host_fields
            values = self._run_oracle(lines[i])
            if values is None:
                if is_invalid:
                    bad += 1
                continue
            if is_invalid:
                valid[i] = True
            for fid in fields_needed:
                if fid.endswith(".*"):
                    # Wildcard target: deliver {relative.name: value} built
                    # from every concrete field under the prefix (the oracle
                    # stores them under their full TYPE:path names).
                    prefix = fid[:-1]  # keep the trailing dot
                    overrides[fid][i] = {
                        k[len(prefix):]: v
                        for k, v in values.items()
                        if k.startswith(prefix)
                    }
                else:
                    overrides[fid][i] = coerce(fid, values.get(fid))

        good = int(B - bad)
        return BatchResult(
            list(lines), buf[:B], lengths[:B], valid, columns, overrides, good, bad
        )

    def _run_oracle(self, line: Union[bytes, str]) -> Optional[Dict[str, Any]]:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="replace")
        try:
            record = self.oracle.parse(line, _CollectingRecord())
        except DissectionFailure:
            return None
        return record.values
