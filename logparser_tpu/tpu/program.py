"""LogFormat -> device split program.

This is the TPU-native replacement for the reference's per-line regex match
(TokenFormatDissector.java:243-275).  Instead of backtracking over one string,
the compiled token list (same compiler as the host oracle path,
logparser_tpu.dissectors.tokenformat) becomes a *split program*: a short list
of vectorizable ops over ``[B, L]`` uint8 buffers —

- ``lit``       match a fixed separator at the cursor,
- ``until_lit`` capture from the cursor to the first occurrence of the next
                separator (the deterministic equivalent of the reference's
                lazy ``.*?`` tokens; greedy tokens are handled optimistically
                the same way),
- ``to_end``    capture the rest of the line.

Every op advances a per-line cursor; validation (separators matched, token
charsets respected, the whole line consumed) yields a per-line validity
mask.  Charsets are supersets of the token regex languages EXCEPT ops
marked ``narrow`` (single-element list approximations): those may
false-invalidate lines the regex accepts — the oracle rescues them —
and must never be used as proof of regex acceptance (plausibility
skips them).
Lines that fail validation are re-parsed on the host oracle path — the
optimistic device split plus oracle fallback is bit-exact with the Java regex
semantics while keeping the hot path free of backtracking.
"""
from __future__ import annotations

from dataclasses import dataclass, field as dataclass_field
from typing import Dict, List, Optional, Tuple


import numpy as np

from ..dissectors.tokenformat import (
    FORMAT_CLF_HEXNUMBER,
    FORMAT_CLF_IP,
    FORMAT_CLF_NON_ZERO_NUMBER,
    FORMAT_CLF_NUMBER,
    FORMAT_HEXNUMBER,
    FORMAT_NO_SPACE_STRING,
    FORMAT_NON_ZERO_NUMBER,
    FORMAT_NUMBER,
    FORMAT_NUMBER_DECIMAL,
    FORMAT_NUMBER_OPTIONAL_DECIMAL,
    FORMAT_STANDARD_TIME_ISO8601,
    FORMAT_STANDARD_TIME_US,
    FixedStringToken,
    Token,
    TokenFormatDissector,
)

# ---------------------------------------------------------------------------
# Charset classes for device-side token validation.  Charsets are SUPERSETS of
# the token regex languages: they can only cause a false-valid on genuinely
# weird lines, never a false-invalid of a line the regex accepts.
# ---------------------------------------------------------------------------

CS_ANY = "any"
CS_NO_SPACE = "no_space"
CS_DIGITS = "digits"
CS_CLF_DIGITS = "clf_digits"        # digits or a lone '-'
CS_HEX = "hex"
CS_CLF_HEX = "clf_hex"
CS_IP = "ip"                        # hex digits, ':', '.', '-'
CS_TIME_US = "time_us"              # 0-9 A-Za-z / : + - and space
CS_TIME_ISO = "time_iso"
CS_NUM_DECIMAL = "num_decimal"      # digits and '.'

_KNOWN_REGEX_CHARSETS = {
    FORMAT_NUMBER: (CS_DIGITS, 1),
    FORMAT_CLF_NUMBER: (CS_CLF_DIGITS, 1),
    FORMAT_NON_ZERO_NUMBER: (CS_DIGITS, 1),
    FORMAT_CLF_NON_ZERO_NUMBER: (CS_CLF_DIGITS, 1),
    FORMAT_HEXNUMBER: (CS_HEX, 1),
    FORMAT_CLF_HEXNUMBER: (CS_CLF_HEX, 1),
    FORMAT_NO_SPACE_STRING: (CS_NO_SPACE, 0),
    FORMAT_CLF_IP: (CS_IP, 1),
    FORMAT_STANDARD_TIME_US: (CS_TIME_US, 26),
    FORMAT_STANDARD_TIME_ISO8601: (CS_TIME_ISO, 25),
    FORMAT_NUMBER_DECIMAL: (CS_NUM_DECIMAL, 3),
    FORMAT_NUMBER_OPTIONAL_DECIMAL: (CS_NUM_DECIMAL, 1),
    "[0-9]+\\.[0-9][0-9][0-9]": (CS_NUM_DECIMAL, 5),  # nginx $msec
    ".*": (CS_ANY, 0),
    ".*?": (CS_ANY, 0),
}

# nginx upstream list regexes (", "-separated elements with ": " redirect
# groups) use their SINGLE-element charset: a one-element list is then
# validated exactly, while any multi-element list (or whitespace-corrupted
# value) contains separator bytes the charset rejects and takes the
# oracle — which is also where multi-element indexing must happen anyway.
# A charset that admitted the separators would make the first-occurrence
# split ambiguous against the regex's backtracking (found by fuzz).


_NARROW_REGEXES: set = set()


def _register_list_regexes() -> None:
    from ..httpd.nginx_modules.upstream import _upstream_list_of

    for elem, cs, mn in (
        (FORMAT_NO_SPACE_STRING, CS_NO_SPACE, 0),
        (FORMAT_NUMBER, CS_DIGITS, 1),
        (FORMAT_NUMBER_DECIMAL, CS_NUM_DECIMAL, 3),
    ):
        regex = _upstream_list_of(elem)
        _KNOWN_REGEX_CHARSETS[regex] = (cs, mn)
        _NARROW_REGEXES.add(regex)


_register_list_regexes()


def _charset_bytes(name: str) -> np.ndarray:
    """256-entry bool table for a charset class."""
    table = np.zeros(256, dtype=bool)
    if name == CS_ANY:
        table[:] = True
    elif name == CS_NO_SPACE:
        table[:] = True
        for ws in b" \t\n\r\x0b\x0c":
            table[ws] = False
    elif name in (CS_DIGITS,):
        table[ord("0") : ord("9") + 1] = True
    elif name == CS_CLF_DIGITS:
        table[ord("0") : ord("9") + 1] = True
        table[ord("-")] = True
    elif name in (CS_HEX, CS_CLF_HEX):
        table[ord("0") : ord("9") + 1] = True
        table[ord("a") : ord("f") + 1] = True
        table[ord("A") : ord("F") + 1] = True
        if name == CS_CLF_HEX:
            table[ord("-")] = True
    elif name == CS_IP:
        table[ord("0") : ord("9") + 1] = True
        table[ord("a") : ord("f") + 1] = True
        table[ord("A") : ord("F") + 1] = True
        table[ord(":")] = True
        table[ord(".")] = True
        table[ord("-")] = True
    elif name == CS_TIME_US:
        table[ord("0") : ord("9") + 1] = True
        table[ord("a") : ord("z") + 1] = True
        table[ord("A") : ord("Z") + 1] = True
        for c in b"/: +-":
            table[c] = True
    elif name == CS_TIME_ISO:
        table[ord("0") : ord("9") + 1] = True
        for c in b"T:+-":
            table[c] = True
    elif name == CS_NUM_DECIMAL:
        table[ord("0") : ord("9") + 1] = True
        table[ord(".")] = True
    else:  # pragma: no cover
        raise ValueError(name)
    return table


@dataclass(frozen=True)
class SplitOp:
    kind: str                     # "lit" | "until_lit" | "to_end"
    lit: bytes = b""              # separator literal for lit/until_lit
    token_index: int = -1         # capture slot for until_lit/to_end
    charset: str = CS_ANY
    min_len: int = 0
    max_len: int = 0              # 0 = unbounded
    # True when `charset` is NARROWER than the token regex's true set
    # (single-element list approximation): validity may use it to route
    # rejects to the oracle, but PLAUSIBILITY must not — its anchoring
    # assumes charset >= regex so that regex-accept implies plausible.
    narrow: bool = False


@dataclass
class TokenSpec:
    """One captured token: which fields it produces."""

    index: int
    charset: str
    min_len: int
    max_len: int = 0              # 0 = unbounded
    narrow: bool = False
    # (type, name) pairs this token emits (TokenOutputField list)
    outputs: List[Tuple[str, str]] = dataclass_field(default_factory=list)


class UnsupportedFormatError(ValueError):
    """The token list cannot be compiled to a deterministic split program
    (e.g. two unbounded tokens with no separator between them); callers fall
    back to the host oracle for the whole format."""


@dataclass
class DeviceProgram:
    log_format: str
    ops: Tuple[SplitOp, ...]
    tokens: List[TokenSpec]
    charset_table: np.ndarray     # [n_charsets, 256] bool
    charset_ids: Dict[str, int]
    max_lit_len: int

    def token_for_field(self, ftype: str, name: str) -> Optional[TokenSpec]:
        for tok in self.tokens:
            if (ftype, name) in tok.outputs:
                return tok
        return None


def _token_charset(token: Token) -> Tuple[str, int, int, bool]:
    known = _KNOWN_REGEX_CHARSETS.get(token.regex)
    if known is not None:
        return known[0], known[1], 0, token.regex in _NARROW_REGEXES
    # The "." regex ($pipe) matches EXACTLY one byte; without the max
    # bound the device would accept arbitrarily long spans the real regex
    # rejects — which can silently diverge instead of falling back (a
    # lazy token further left absorbs the difference).  Only the literal
    # dot is modeled: other single-char classes/escapes would need their
    # byte set as the charset to stay sound.
    if token.regex == ".":
        return CS_ANY, 1, 1, False
    return CS_ANY, 0, 0, False


def compile_device_program(dissector: TokenFormatDissector) -> DeviceProgram:
    """Compile a (set_log_format-ed) token-format dissector's token list into
    a device split program."""
    tokens = dissector.log_format_tokens
    if not tokens:
        raise UnsupportedFormatError("empty format")

    ops: List[SplitOp] = []
    specs: List[TokenSpec] = []
    i = 0
    n = len(tokens)
    while i < n:
        tok = tokens[i]
        if isinstance(tok, FixedStringToken):
            ops.append(SplitOp("lit", tok.regex.encode("utf-8")))
            i += 1
            continue
        charset, min_len, max_len, narrow = _token_charset(tok)
        spec = TokenSpec(len(specs), charset, min_len, max_len, narrow,
                         [(f.type, f.name) for f in tok.output_fields])
        specs.append(spec)
        # Find the terminating separator: the next fixed token.
        if i + 1 < n:
            nxt = tokens[i + 1]
            if isinstance(nxt, FixedStringToken):
                ops.append(
                    SplitOp("until_lit", nxt.regex.encode("utf-8"),
                            spec.index, charset, min_len, max_len, narrow)
                )
                i += 2  # the separator is consumed by until_lit
                continue
            # Two value tokens back to back: deterministic only if this one
            # has a bounded charset that excludes the next token's first
            # character — not supported in v1.
            raise UnsupportedFormatError(
                f"adjacent value tokens without separator in {dissector.get_log_format()!r}"
            )
        ops.append(SplitOp("to_end", b"", spec.index, charset, min_len,
                           max_len, narrow))
        i += 1

    return _finish_program(dissector, ops, specs)


def _finish_program(
    dissector: TokenFormatDissector,
    ops: List[SplitOp],
    specs: List[TokenSpec],
) -> DeviceProgram:
    charset_names = sorted({s.charset for s in specs} | {CS_ANY})
    charset_ids = {name: idx for idx, name in enumerate(charset_names)}
    table = np.stack([_charset_bytes(name) for name in charset_names])

    max_lit = max((len(op.lit) for op in ops if op.lit), default=1)
    return DeviceProgram(
        log_format=dissector.get_log_format() or "",
        ops=tuple(ops),
        tokens=specs,
        charset_table=table,
        charset_ids=charset_ids,
        max_lit_len=max_lit,
    )


def compile_plausibility_program(
    dissector: TokenFormatDissector,
) -> DeviceProgram:
    """Separator-order program for a format compile_device_program rejects.

    Used ONLY for the plausibility bit (multi-format registration-priority
    contest + the definitely-bad filter), never for value capture.  The
    constructs that make a format uncompilable — adjacent value tokens
    with no separator — collapse into ONE ``CS_ANY`` capture, which keeps
    every literal separator in order.  Plausibility's contract
    (regex-accept implies plausible, compute_split docstring) survives
    the collapse: it only needs charset >= regex and separator
    subsequence existence, and ``CS_ANY`` is a superset of everything.
    An empty format compiles to a zero-op program whose plausibility is
    True everywhere (sound: over-approximation)."""
    tokens = dissector.log_format_tokens
    ops: List[SplitOp] = []
    specs: List[TokenSpec] = []
    i = 0
    n = len(tokens)
    while i < n:
        tok = tokens[i]
        if isinstance(tok, FixedStringToken):
            ops.append(SplitOp("lit", tok.regex.encode("utf-8")))
            i += 1
            continue
        # A run of adjacent value tokens becomes one CS_ANY capture; a
        # single value token keeps its real charset (better final-to_end
        # anchoring; still a superset of the regex language).
        j = i
        while j < n and not isinstance(tokens[j], FixedStringToken):
            j += 1
        if j - i == 1:
            charset, min_len, max_len, narrow = _token_charset(tok)
        else:
            charset, min_len, max_len, narrow = CS_ANY, 0, 0, False
        spec = TokenSpec(len(specs), charset, min_len, max_len, narrow, [])
        specs.append(spec)
        if j < n:
            nxt = tokens[j]
            ops.append(
                SplitOp("until_lit", nxt.regex.encode("utf-8"),
                        spec.index, charset, min_len, max_len, narrow)
            )
            i = j + 1
        else:
            ops.append(SplitOp("to_end", b"", spec.index, charset, min_len,
                               max_len, narrow))
            i = j
    return _finish_program(dissector, ops, specs)
