"""Vectorized post-stages: typed decoding of captured spans on device.

These replace the reference's per-line sub-dissectors on the hot path:
- :func:`parse_long_spans` — digit spans -> int64 (CLF '-' aware), replacing
  Value.getLong / ConvertCLFIntoNumber.
- :func:`parse_apache_timestamp` — ``dd/MMM/yyyy:HH:mm:ss ZZ`` spans ->
  epoch millis, replacing TimeStampDissector's formatter parse for the fixed
  Apache layout (TimeStampDissector.java:404-424).  Fixed offsets + a month
  name lookup table + days-from-civil integer math: pure VPU arithmetic.
- :func:`split_firstline` — "GET /x HTTP/1.1" spans -> method/uri/protocol
  sub-spans (HttpFirstLineDissector.java:59-63 semantics: first space, last
  space, protocol validated as ``HTTP/``).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp

MAX_LONG_DIGITS = 18

# Month names; matched via (l0*26 + l1)*26 + l2 hash compares in
# parse_apache_timestamp.
_MONTHS = ["jan", "feb", "mar", "apr", "may", "jun",
           "jul", "aug", "sep", "oct", "nov", "dec"]


def _pad_cols(x: jnp.ndarray, w: int) -> jnp.ndarray:
    B, cur = x.shape
    if cur >= w:
        return x[:, :w]
    return jnp.pad(x, ((0, 0), (0, w - cur)))


def gather_span_bytes(buf: jnp.ndarray, start: jnp.ndarray, width: int) -> jnp.ndarray:
    """Extract `width` bytes per line beginning at start: [B, width].

    TPU gathers are scalar-slow, so this is a log-shift alignment instead:
    decompose the per-row shift into its bits and apply each power-of-two
    shift as a static slice + select.  The working width narrows as high bits
    are consumed, so total work is ~(width * log2(L) + L) elements — a couple
    of [B, L]-equivalent vector passes, no gather.  Bytes shifted in from
    beyond the row are 0 (callers' validity masks already exclude them)."""
    B, L = buf.shape
    width = min(width, L)
    x = buf
    for j in reversed(range(max(1, (L - 1).bit_length()))):
        k = 1 << j
        need = width + k - 1
        bit = ((start >> j) & 1) == 1
        x = jnp.where(bit[:, None], _pad_cols(x[:, k:], need), _pad_cols(x, need))
    return x[:, :width]


def parse_long_spans(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    clf: bool = False,
    extract=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Spans of ASCII digits -> int64.

    Returns (value, is_null, ok).  With ``clf`` a lone '-' yields
    is_null=True (the reference maps '-' to null, ApacheHttpdLogFormatDissector
    decodeExtractedValue :176-178).
    """
    extract = extract or gather_span_bytes
    n = end - start
    bytes_ = extract(buf, start, MAX_LONG_DIGITS)
    col = jax.lax.broadcasted_iota(jnp.int32, (buf.shape[0], MAX_LONG_DIGITS), 1)
    in_span = col < n[:, None]
    digits = (bytes_ - np.uint8(ord("0"))).astype(jnp.int32)
    digit_ok = (digits >= 0) & (digits <= 9)

    # int64 is unavailable on device without global x64; accumulate two int32
    # limbs (leading digits / trailing 9 digits) and let the host combine:
    # value = hi * 10^min(n,9) ... see combine_long_limbs.
    hi = jnp.zeros(buf.shape[0], dtype=jnp.int32)
    lo = jnp.zeros(buf.shape[0], dtype=jnp.int32)
    for i in range(MAX_LONG_DIGITS):
        take = in_span[:, i]
        # Digit i belongs to the 'lo' limb when it is within the last 9
        # digits of the span, i.e. i >= n - 9.
        is_lo = take & (i >= (n - 9))
        is_hi = take & ~is_lo
        hi = jnp.where(is_hi, hi * 10 + digits[:, i], hi)
        lo = jnp.where(is_lo, lo * 10 + digits[:, i], lo)

    is_dash = (n == 1) & (bytes_[:, 0] == np.uint8(ord("-")))
    all_digits = jnp.all(digit_ok | ~in_span, axis=1)
    ok = (
        ((n > 0) & (n <= MAX_LONG_DIGITS) & all_digits)
        | (is_dash if clf else False)
    )
    is_null = is_dash & clf
    return (hi, lo, jnp.minimum(n, 9)), is_null, ok


def combine_long_limbs(hi, lo, lo_digits, is_null) -> np.ndarray:
    """Host-side limb combine -> int64 numpy column (null slots -1)."""
    value = np.asarray(hi, dtype=np.int64) * np.power(
        10, np.asarray(lo_digits, dtype=np.int64)
    ) + np.asarray(lo, dtype=np.int64)
    value[np.asarray(is_null)] = -1
    return value


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Days since 1970-01-01 (proleptic Gregorian), vectorized int32/64."""
    y = y - (m <= 2)
    era = jnp.floor_divide(jnp.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400
    mp = jnp.mod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _two_digits(b: jnp.ndarray, i: int) -> jnp.ndarray:
    return (
        (b[:, i] - np.uint8(ord("0"))).astype(jnp.int32) * 10
        + (b[:, i + 1] - np.uint8(ord("0"))).astype(jnp.int32)
    )


def parse_apache_timestamp(
    buf: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray, extract=None
) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """``dd/MMM/yyyy:HH:mm:ss +ZZZZ`` spans -> ((days, sec_of_day), ok).

    Layout offsets: dd=0..1 /  MMM=3..5 / yyyy=7..10 : HH=12 : mm=15 : ss=18
    ' ' sign=21 offHH=22 offMM=24.
    """
    extract = extract or gather_span_bytes
    b = extract(buf, start, 26)
    width_ok = (end - start) == 26

    day = _two_digits(b, 0)
    lower = b | np.uint8(0x20)
    l0 = (lower[:, 3] - np.uint8(ord("a"))).astype(jnp.int32)
    l1 = (lower[:, 4] - np.uint8(ord("a"))).astype(jnp.int32)
    l2 = (lower[:, 5] - np.uint8(ord("a"))).astype(jnp.int32)
    letters_ok = (
        (l0 >= 0) & (l0 < 26) & (l1 >= 0) & (l1 < 26) & (l2 >= 0) & (l2 < 26)
    )
    # 12 vector compares instead of a table gather (TPU gathers are slow).
    h = (l0 * 26 + l1) * 26 + l2
    month = jnp.zeros(buf.shape[0], dtype=jnp.int32)
    for m, name in enumerate(_MONTHS, start=1):
        hm = ((ord(name[0]) - 97) * 26 + (ord(name[1]) - 97)) * 26 + (
            ord(name[2]) - 97
        )
        month = jnp.where(h == hm, m, month)

    year = (
        (b[:, 7] - np.uint8(ord("0"))).astype(jnp.int32) * 1000
        + (b[:, 8] - np.uint8(ord("0"))).astype(jnp.int32) * 100
        + _two_digits(b, 9)
    )
    hour = _two_digits(b, 12)
    minute = _two_digits(b, 15)
    second = _two_digits(b, 18)

    sign = jnp.where(b[:, 21] == np.uint8(ord("-")), -1, 1).astype(jnp.int32)
    off_h = _two_digits(b, 22)
    off_m = _two_digits(b, 24)
    offset_s = sign * (off_h * 3600 + off_m * 60)

    seps_ok = (
        (b[:, 2] == np.uint8(ord("/")))
        & (b[:, 6] == np.uint8(ord("/")))
        & (b[:, 11] == np.uint8(ord(":")))
        & (b[:, 14] == np.uint8(ord(":")))
        & (b[:, 17] == np.uint8(ord(":")))
        & (b[:, 20] == np.uint8(ord(" ")))
        & ((b[:, 21] == np.uint8(ord("+"))) | (b[:, 21] == np.uint8(ord("-"))))
    )
    # Digit-check every numeric byte explicitly.  day/hour/min/sec garbage is
    # caught by the range bounds below, but year and tz-offset values are
    # otherwise unbounded — without this, a non-digit byte yields different
    # (both "ok") arithmetic under the uint8 jnp path vs the int32 Pallas
    # path, and the host layout rejects such lines outright.
    digits_ok = jnp.ones(buf.shape[0], dtype=bool)
    for i in (0, 1, 7, 8, 9, 10, 12, 13, 15, 16, 18, 19, 22, 23, 24, 25):
        digits_ok = digits_ok & (
            (b[:, i] >= np.uint8(ord("0"))) & (b[:, i] <= np.uint8(ord("9")))
        )
    # Day-in-month with leap years, so the device accepts exactly what the
    # host layout accepts (no silent wrong epochs bypassing the oracle).
    leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
    thirty = (month == 4) | (month == 6) | (month == 9) | (month == 11)
    dim = jnp.where(thirty, 30, jnp.where(month == 2, jnp.where(leap, 29, 28), 31))
    fields_ok = (
        (month >= 1)
        & (day >= 1)
        & (day <= dim)
        & (hour <= 23)
        & (minute <= 59)
        & (second <= 60)
    )
    # Leap second: the host layout clamps 60 -> 59 (java.time SMART).
    second = jnp.minimum(second, 59)

    days = _days_from_civil(year, month, day)
    sec_of_day = hour * 3600 + minute * 60 + second - offset_s
    ok = width_ok & letters_ok & seps_ok & digits_ok & fields_ok
    # Combined on host: epoch_ms = (days * 86400 + sec_of_day) * 1000 (int64).
    return (days, sec_of_day), ok


def combine_epoch(days, sec_of_day) -> np.ndarray:
    """Host-side combine -> epoch milliseconds int64 numpy column."""
    return (
        np.asarray(days, dtype=np.int64) * 86400
        + np.asarray(sec_of_day, dtype=np.int64)
    ) * 1000


def split_firstline(
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
) -> Dict[str, jnp.ndarray]:
    """"METHOD URI PROTO" span -> method/uri/protocol sub-spans.

    Mirrors HttpFirstLineDissector: method = up to the first space, protocol =
    after the last space (only when it matches ``xxx/d.d`` shape — otherwise
    the truncated-line fallback applies: protocol absent, uri to the end).
    ``has_protocol`` distinguishes the two cases; fully garbage lines (no
    space at all) get ok=False.
    """
    extract = extract or gather_span_bytes
    B, L = buf.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])
    is_space = (buf == np.uint8(ord(" "))) & in_span

    first_space = jnp.min(jnp.where(is_space, pos, L), axis=1)
    last_space = jnp.max(jnp.where(is_space, pos, -1), axis=1)

    has_space = first_space < L
    method_start = start
    method_end = jnp.where(has_space, first_space, start)

    # Protocol candidate: after the last space; valid only when it matches
    # HTTP/[0-9]+\.[0-9]+ exactly (the 3-part regex arm; otherwise the
    # truncated-line fallback applies).
    proto_start = jnp.where(has_space, last_space + 1, end)
    head = extract(buf, proto_start, 5)
    head_ok = (
        (head[:, 0] == np.uint8(ord("H")))
        & (head[:, 1] == np.uint8(ord("T")))
        & (head[:, 2] == np.uint8(ord("T")))
        & (head[:, 3] == np.uint8(ord("P")))
        & (head[:, 4] == np.uint8(ord("/")))
    )
    ver = (pos >= (proto_start + 5)[:, None]) & (pos < end[:, None])
    is_digit = (buf >= np.uint8(ord("0"))) & (buf <= np.uint8(ord("9")))
    is_dot = buf == np.uint8(ord("."))
    ver_chars_ok = jnp.all(is_digit | is_dot | ~ver, axis=1)
    one_dot = jnp.sum(jnp.where(is_dot & ver, 1, 0), axis=1) == 1
    last_b = extract(buf, jnp.maximum(end - 1, 0), 1)[:, 0]
    first_ver = extract(buf, proto_start + 5, 1)[:, 0]
    ver_ok = (
        ((end - proto_start) >= 8)
        & ver_chars_ok
        & one_dot
        & (first_ver >= np.uint8(ord("0"))) & (first_ver <= np.uint8(ord("9")))
        & (last_b >= np.uint8(ord("0"))) & (last_b <= np.uint8(ord("9")))
    )
    has_protocol = has_space & (last_space > first_space) & head_ok & ver_ok

    uri_start = jnp.where(has_space, first_space + 1, end)
    uri_end = jnp.where(has_protocol, last_space, end)

    return {
        "method_start": method_start,
        "method_end": method_end,
        "uri_start": uri_start,
        "uri_end": uri_end,
        "proto_start": jnp.where(has_protocol, proto_start, end),
        "proto_end": end,
        "has_protocol": has_protocol,
        "ok": has_space,
    }
