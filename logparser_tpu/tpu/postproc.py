"""Vectorized post-stages: typed decoding of captured spans on device.

These replace the reference's per-line sub-dissectors on the hot path:
- :func:`parse_long_spans` — digit spans -> int64 (CLF '-' aware), replacing
  Value.getLong / ConvertCLFIntoNumber.
- :func:`parse_secmillis_spans` — ``"1483455396.639"`` decimal spans ->
  epoch-millis limbs, replacing ConvertSecondsWithMillisStringDissector
  (nginx ``$msec``/``$request_time``).
- :func:`split_firstline` — "GET /x HTTP/1.1" spans -> method/uri/protocol
  sub-spans (HttpFirstLineDissector.java:59-63 semantics: first space, last
  space, protocol validated as ``HTTP/``).

Timestamp layouts are handled generically by ``tpu/timeparse.py`` (any
fixed-width TimeLayout compiles to a device program); the epoch/derived
output math happens host-side in ``tpu/timefields.py``.
"""
from __future__ import annotations

import functools

from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

# Full int64 frame: every value of <= 19 digits decodes exactly on device
# (the widest int64, Long.MAX_VALUE = 9223372036854775807, has 19).  Runs
# LONGER than 19 digits stay device-valid too: parse_long_spans flags them
# ``big`` and the batch runtime patches their exact value from the byte
# buffer host-side (reference semantics: TokenParser FORMAT_NUMBER has no
# width bound; values beyond Long range deliver through the STRING cast).
MAX_LONG_DIGITS = 19
LONG_MAX = (1 << 63) - 1
# uint64 powers of ten for the host-side frame combine (10^19 overflows
# int64 but not uint64; mixed-dtype np.power would promote to float64).
_POW10_U64 = np.array([10 ** k for k in range(MAX_LONG_DIGITS + 1)],
                      dtype=np.uint64)


def pow10_weights(w: int) -> jnp.ndarray:
    """[w] descending powers of ten (10^(w-1) .. 10^0) for digit-window
    dot products.  Built from iota rather than a numpy constant; XLA
    folds it to a constant either way."""
    return jnp.int32(10) ** (
        w - 1 - jax.lax.broadcasted_iota(jnp.int32, (w,), 0)
    )


def shift_zero(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Left-shift columns by k, zero-filling the tail — the single shared
    shift primitive (pipeline re-exports it); every consumer masks bytes
    past the span/line end."""
    if k <= 0:
        return x
    B, L = x.shape
    if k >= L:
        return jnp.zeros_like(x)
    return jnp.concatenate([x[:, k:], jnp.zeros((B, k), x.dtype)], axis=1)


def _pad_cols(x: jnp.ndarray, w: int) -> jnp.ndarray:
    B, cur = x.shape
    if cur >= w:
        return x[:, :w]
    return jnp.pad(x, ((0, 0), (0, w - cur)))


def gather_span_bytes(buf: jnp.ndarray, start: jnp.ndarray, width: int) -> jnp.ndarray:
    """Extract `width` bytes per line beginning at start: [B, width].

    TPU gathers are scalar-slow, so this is a log-shift alignment instead:
    decompose the per-row shift into its bits and apply each power-of-two
    shift as a static slice + select.  The working width narrows as high bits
    are consumed, so total work is ~(width * log2(L) + L) elements — a couple
    of [B, L]-equivalent vector passes, no gather.  Bytes shifted in from
    beyond the row are 0 (callers' validity masks already exclude them)."""
    B, L = buf.shape
    width = min(width, L)
    x = buf
    for j in reversed(range(max(1, (L - 1).bit_length()))):
        k = 1 << j
        need = width + k - 1
        bit = ((start >> j) & 1) == 1
        x = jnp.where(bit[:, None], _pad_cols(x[:, k:], need), _pad_cols(x, need))
    return x[:, :width]


def parse_long_spans(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    clf: bool = False,
    extract=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Spans of ASCII digits -> int64 limbs, fully vectorized.

    Returns ((hi, lo, d18, ndig), is_null, ok, big).  The limbs use a
    FIXED 19-wide left-aligned frame: ``hi`` is the dot product of window
    columns 0..8 with 10^(8-i), ``lo`` of columns 9..17 with 10^(17-i),
    ``d18`` the raw 19th digit (bytes past the span masked to digit 0),
    and ``ndig`` the span's digit count — so the host combine is one
    exact uint64 integer division (combine_long_limbs), and the device
    needs no per-column scalar rounds.  ``big`` marks runs longer than 19
    digits: the frame cannot carry them, so the caller either packs span
    coordinates for a host byte-patch (direct token numerics, reference
    Long-overflow semantics) or clears ok to route the line to the
    oracle.  For big rows only the first 19 bytes are digit-checked on
    device; the host patch validates the tail.  With ``clf`` a lone '-'
    yields is_null=True (the reference maps '-' to null,
    ApacheHttpdLogFormatDissector decodeExtractedValue :176-178).
    """
    extract = extract or gather_span_bytes
    n = end - start
    bytes_ = extract(buf, start, MAX_LONG_DIGITS)
    col = jax.lax.broadcasted_iota(jnp.int32, (buf.shape[0], MAX_LONG_DIGITS), 1)
    in_span = col < n[:, None]
    digits = (bytes_ - np.uint8(ord("0"))).astype(jnp.int32)
    digit_ok = (digits >= 0) & (digits <= 9)
    d = jnp.where(in_span, digits, 0)

    p9 = pow10_weights(9)
    hi = jnp.sum(d[:, :9] * p9, axis=1).astype(jnp.int32)
    lo = jnp.sum(d[:, 9:18] * p9, axis=1).astype(jnp.int32)
    d18 = d[:, 18].astype(jnp.int32)

    is_dash = (n == 1) & (bytes_[:, 0] == np.uint8(ord("-")))
    window_digits = jnp.all(digit_ok | ~in_span, axis=1)
    big = n > MAX_LONG_DIGITS
    ok = ((n > 0) & window_digits) | (is_dash if clf else False)
    is_null = is_dash & clf
    return (
        (hi, lo, d18, jnp.clip(n, 0, MAX_LONG_DIGITS)),
        is_null, ok, big,
    )


def combine_long_limbs(hi, lo, d18, ndig, is_null):
    """Host-side frame combine -> (int64 values, overflow mask, uint64
    frame values).

    The limbs are the fixed-frame dot products of parse_long_spans: the
    19-digit left-aligned value is hi*10^10 + lo*10 + d18 with
    (19 - ndig) trailing zero digits, so dividing by 10^(19-ndig) is
    exact.  The combine runs in uint64 (10^19-1 overflows int64);
    ``overflow`` marks rows whose exact value exceeds Long.MAX_VALUE —
    the caller delivers those through the reference's STRING-cast
    overflow path (the int64 column entry is clamped, never read).
    Null slots -1.  Rows the device flagged ``big`` carry span
    coordinates in ``hi`` and must be masked out by the caller."""
    hi_u = np.asarray(hi).astype(np.uint64)
    lo_u = np.asarray(lo).astype(np.uint64)
    d_u = np.asarray(d18).astype(np.uint64)
    frame = hi_u * np.uint64(10 ** 10) + lo_u * np.uint64(10) + d_u
    shift = MAX_LONG_DIGITS - np.asarray(ndig, dtype=np.int64)
    wide = frame // _POW10_U64[np.clip(shift, 0, MAX_LONG_DIGITS)]
    overflow = wide > np.uint64(LONG_MAX)
    value = np.where(overflow, np.uint64(LONG_MAX), wide).astype(np.int64)
    value[np.asarray(is_null)] = -1
    return value, overflow, wide


def parse_secmillis_spans(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
) -> Tuple[
    Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    jnp.ndarray, jnp.ndarray, jnp.ndarray,
]:
    """``"<seconds>.<3-digit millis>"`` spans -> (seconds limbs, millis).

    Returns ((hi, lo, ndig), millis, is_null, ok): the seconds part goes
    through :func:`parse_long_spans` (fixed-frame limbs over the sub-span
    before the dot), the 3 millis digits decode from a fixed window at the
    span end; the host combines ``seconds * 1000 + millis``.  ok requires
    the exact ``[0-9]+\\.[0-9]{3}`` shape the host regex/converter accepts
    (ConvertSecondsWithMillisStringDissector semantics), incl. the old
    total-digits cap (w <= 19).
    """
    extract = extract or gather_span_bytes
    w = end - start
    sec_limbs, _, sec_ok, sec_big = parse_long_spans(
        buf, start, jnp.maximum(end - 4, start), extract=extract
    )
    # One width-4 window serves both the dot and the three millis digits.
    win = extract(buf, jnp.maximum(end - 4, 0), 4)
    dot = win[:, 0]
    md = (win[:, 1:4] - np.uint8(ord("0"))).astype(jnp.int32)
    m_ok = jnp.all((md >= 0) & (md <= 9), axis=1)
    millis = md[:, 0] * 100 + md[:, 1] * 10 + md[:, 2]
    ok = (
        # Total width cap unchanged from the 18-digit era (nd = w-1 <= 18):
        # seconds spans stay <= 15 digits, so seconds*1000+millis can
        # never overflow int64 and the big/overflow machinery of the
        # plain long path is unreachable here.
        (w >= 5)
        & (w <= 19)
        & sec_ok
        & ~sec_big
        & m_ok
        & (dot == np.uint8(ord(".")))
    )
    is_null = jnp.zeros(buf.shape[0], dtype=bool)
    return sec_limbs, millis, is_null, ok


def split_uri_fast(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
    dash=None,
    need_authority: bool = True,
    window: Optional[int] = None,
) -> Dict[str, jnp.ndarray]:
    """Fast-path URI split: repair-free URIs -> sub-spans on device.

    Mirrors HttpUriDissector (dissectors/uri.py; HttpUriDissector.java:52-63)
    for spans whose repair-chain outcome the device (plus per-row fix
    materialization) can model exactly: relative URIs, scheme-less paths,
    absolute URLs with a server-based or registry-based authority
    (IPv6 ``[...]`` literals included — see the inline note: the encode
    step makes them registry-based on the host too), and opaque
    scheme-URIs (``mailto:``).  ``clean`` is False only for rows whose
    repair stages the device cannot reproduce; those re-parse on the host
    oracle (the caller folds ``ok`` into line validity):

    - bytes >= 0x7F or < 0x20 (the host passes high bytes through
      byte-to-latin-1 mojibake-preserving, which a UTF-8 span decode
      cannot reproduce),
    - ``#`` (fragment handling, =#/#&/double-# artifacts rewrite),
    - ``;`` (sound over-approximation of the HTML-entity unescape:
      every entity needs a ``;``),
    - more than one ``?``, or a ``?`` that is not the first
      query-separator occurrence (the ?->& normalization would rewrite
      bytes inside the span),
    - a scheme that fails ``[A-Za-z][A-Za-z0-9+.-]*`` (raises on the
      host — the oracle rejects the line identically),
    - an absolute URL with an actual digits-only port longer than 19
      digits (the host parses it with arbitrary precision).

    Absolute URLs (JavaUri semantics, dissectors/uri.py:105-168): scheme =
    up to the first ``:`` when it precedes any ``/``/separator; a ``://``
    introduces an authority ending at the next ``/`` or query separator;
    the LAST ``@`` splits userinfo; the last ``:`` in the remainder splits
    a digits-only port.  A non-server authority (host charset outside
    ``[A-Za-z0-9.-]`` — which covers ``%`` and every encode-set byte, so
    IPv6 literals and %-escaped hosts land here — or a non-numeric port)
    is registry-based: userinfo/host/port are all null, path/query still
    deliver.  Opaque URIs deliver protocol + path (``first_colon+1`` to
    the first separator) + query; authority parts are null.  Scheme-less
    spans not starting with ``/`` ("example.com/x") have no authority:
    the whole head is path, protocol/userinfo/host/port null (exactly
    _URI_SPLIT's behavior).

    Percent signs and printable encode-set bytes in path/query/userinfo
    do NOT force the oracle: they only flag per-row host
    micro-materialization (orders of magnitude cheaper than a full oracle
    re-parse).  ``path_fix`` marks rows whose path contains ``%`` (the
    host delivers the path percent-DECODED after the encode + %25-repair
    steps; encode-set bytes alone are an encode->decode identity and need
    no fix).  ``query_fix`` marks rows whose query contains a bad escape
    (repaired to ``%25``) or an encode-set byte (delivered %-escaped).
    ``userinfo_fix`` marks rows with ``%`` in the userinfo (the host
    percent-decodes it).  The ``%``-repair inserts only digits and the
    encode step only ``%XX`` triples, so neither can create or destroy
    separators — span boundaries are unaffected.

    An empty span — or a lone ``-`` when the caller passes the token-level
    CLF ``dash`` mask — is clean: every output is null (the host dissector
    delivers nothing).  The query span keeps its leading separator byte;
    when that byte is ``?`` the host delivers it as ``&`` (the ?&
    normalization) — the ``amp`` flag tells the materializer to swap it.

    ``window`` bounds the scan domain exactly as in :func:`split_csr`: the
    URI span is gathered into a compact [B, window] buffer, the split runs
    there, and every positional output is rebased by the span start.  Rows
    whose span exceeds the window raise ``overflow`` (with ``ok`` held
    True so they route as a capacity defer, not a device reject); the
    caller folds that into the adaptive CSR response — doubled slots scale
    the window along, so long-URI corpora pay bounded recompiles, and at
    the slot cap the rows stay oracle-bound.  Outputs are bit-identical to
    the unwindowed split for every row that fits.
    """
    B, L = buf.shape
    if window is not None and int(window) < L:
        W = int(window)
        span = end - start
        widx = jnp.clip(
            start[:, None] + jax.lax.broadcasted_iota(jnp.int32, (1, W), 1),
            0, L - 1,
        )
        wbuf = jnp.take_along_axis(buf, jnp.broadcast_to(widx, (B, W)), axis=1)
        res = split_uri_fast(
            wbuf,
            jnp.zeros_like(start),
            jnp.minimum(span, W),
            dash=dash,
            need_authority=need_authority,
        )
        for name, v in list(res.items()):
            if name.endswith("_start") or name.endswith("_end"):
                res[name] = v + start
        over = span > W
        res["ok"] = res["ok"] | over
        res["overflow"] = over
        return res
    extract = extract or gather_span_bytes
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])
    width = end - start
    empty = width == 0
    if dash is None:
        dash = jnp.zeros(B, dtype=bool)
    all_null = empty | dash

    is_q = (buf == np.uint8(ord("?"))) & in_span
    is_amp = (buf == np.uint8(ord("&"))) & in_span
    first_sep = jnp.min(
        jnp.where(is_q | is_amp, pos, L), axis=1
    ).astype(jnp.int32)
    first_sep = jnp.minimum(first_sep, end)

    # Oracle-only bytes: controls and >= 0x7F (the host chain passes raw
    # high bytes through byte-to-latin-1 — mojibake-preserving — which a
    # UTF-8 span decode cannot reproduce), '#' (fragment handling and the
    # =#/#&/double-# rewrites) and ';' (sound over-approximation of the
    # HTML-entity unescape: every entity needs a ';').
    bad = (buf < np.uint8(0x20)) | (buf >= np.uint8(0x7F))
    bad = bad | (buf == np.uint8(ord("#"))) | (buf == np.uint8(ord(";")))
    clean = ~jnp.any(bad & in_span, axis=1)

    # Printable encode-set bytes (URIUtil's escape set minus the oracle
    # bytes above).  These no longer force the oracle: the encode step
    # %-escapes them, after which (a) in an authority the host charset /
    # port-digit checks fail exactly as they do on the RAW bytes, so the
    # registry-based outcome is identical, (b) in the path (and userinfo)
    # the later percent-DECODE undoes the escape — a byte-identity round
    # trip — and (c) in the query they are delivered ESCAPED, which the
    # per-row fix materializer reproduces (fix modes run the encode step
    # first).
    from ..dissectors.uri import ENCODE_PRINTABLE

    enc = None
    for ch in ENCODE_PRINTABLE:
        m = (buf == np.uint8(ch)) & in_span
        enc = m if enc is None else (enc | m)

    # '?' discipline: at most one, and only at the first separator.
    q_count = jnp.sum(jnp.where(is_q, 1, 0), axis=1)
    first_q = jnp.min(jnp.where(is_q, pos, L), axis=1).astype(jnp.int32)
    clean = clean & (
        (q_count == 0) | ((q_count == 1) & (first_q == first_sep))
    )

    is_pct = (buf == np.uint8(ord("%"))) & in_span
    shift = shift_zero
    nxt1 = shift(buf, 1)
    nxt2 = shift(buf, 2)

    def _is_hex(x):
        return (
            ((x >= np.uint8(ord("0"))) & (x <= np.uint8(ord("9"))))
            | ((x >= np.uint8(ord("a"))) & (x <= np.uint8(ord("f"))))
            | ((x >= np.uint8(ord("A"))) & (x <= np.uint8(ord("F"))))
        )

    pct_bad = is_pct & ~(_is_hex(nxt1) & _is_hex(nxt2) & (pos + 2 < end[:, None]))

    lead = extract(buf, start, 1)[:, 0]
    relative = (~all_null) & (lead == np.uint8(ord("/")))

    # ---- absolute/scheme-less analysis (JavaUri semantics) -----------
    is_digit = (buf >= np.uint8(ord("0"))) & (buf <= np.uint8(ord("9")))
    is_alpha = (
        ((buf >= np.uint8(ord("A"))) & (buf <= np.uint8(ord("Z"))))
        | ((buf >= np.uint8(ord("a"))) & (buf <= np.uint8(ord("z"))))
    )
    is_colon = (buf == np.uint8(ord(":"))) & in_span
    is_slash = (buf == np.uint8(ord("/"))) & in_span

    first_colon = jnp.min(jnp.where(is_colon, pos, L), axis=1).astype(jnp.int32)
    first_slash = jnp.min(jnp.where(is_slash, pos, L), axis=1).astype(jnp.int32)
    limit = jnp.minimum(jnp.minimum(first_slash, first_sep), end)
    has_scheme = (first_colon < limit) & (first_colon > start)

    scheme_cs = (
        is_alpha | is_digit
        | (buf == np.uint8(ord("+")))
        | (buf == np.uint8(ord(".")))
        | (buf == np.uint8(ord("-")))
    )
    in_scheme = (pos > start[:, None]) & (pos < first_colon[:, None])
    lead_alpha = (
        ((lead >= np.uint8(ord("A"))) & (lead <= np.uint8(ord("Z"))))
        | ((lead >= np.uint8(ord("a"))) & (lead <= np.uint8(ord("z"))))
    )
    scheme_ok = lead_alpha & jnp.all(scheme_cs | ~in_scheme, axis=1)

    d2 = extract(buf, first_colon + 1, 2)
    dslash = (
        (d2[:, 0] == np.uint8(ord("/")))
        & (d2[:, 1] == np.uint8(ord("/")))
        & (first_colon + 3 <= end)
    )
    auth_start = first_colon + 3
    slash_a = jnp.min(
        jnp.where(is_slash & (pos >= auth_start[:, None]), pos, L), axis=1
    ).astype(jnp.int32)
    auth_end = jnp.minimum(jnp.minimum(slash_a, first_sep), end)
    if need_authority:
        in_auth = (pos >= auth_start[:, None]) & (pos < auth_end[:, None])
        at = jnp.max(
            jnp.where((buf == np.uint8(ord("@"))) & in_auth, pos, -1), axis=1
        ).astype(jnp.int32)
        has_at = at >= 0
        rest_start = jnp.where(has_at, at + 1, auth_start)
        colon2 = jnp.max(
            jnp.where(
                is_colon & (pos >= rest_start[:, None])
                & (pos < auth_end[:, None]),
                pos, -1,
            ),
            axis=1,
        ).astype(jnp.int32)
        has_pcolon = colon2 >= 0
        port_start = colon2 + 1
        port_len = auth_end - port_start
        port_empty = port_len <= 0
        in_port = has_pcolon[:, None] & (pos >= port_start[:, None]) & (
            pos < auth_end[:, None]
        )
        port_digits = jnp.all(is_digit | ~in_port, axis=1)
        host_end = jnp.where(
            has_pcolon & (port_empty | port_digits), colon2, auth_end
        )
        in_host = (pos >= rest_start[:, None]) & (pos < host_end[:, None])
        host_cs = (
            is_alpha | is_digit
            | (buf == np.uint8(ord(".")))
            | (buf == np.uint8(ord("-")))
        )
        host_ok_cs = jnp.all(host_cs | ~in_host, axis=1)
        registry = (~host_ok_cs) | (has_pcolon & ~port_empty & ~port_digits)

        # IPv6 '[...]' literals need no dedicated branch: the host chain
        # %-escapes '[' and ']' BEFORE java.net.URI ever sees the
        # authority, so "[::1]" can never take the URI IPv6-literal parse —
        # the escaped host fails the charset check and the authority is
        # registry-based (host/userinfo/port null).  On device the RAW
        # '['/':' bytes fail host_cs / port_digits the same way, landing
        # on the identical registry outcome.  A '%' in the host or port
        # region likewise survives the repair ('%25' keeps the '%') and
        # fails the same checks — no oracle needed.  Userinfo is the one
        # authority part the host percent-DECODES, so rows with '%' there
        # flag per-row fix materialization instead.
        ui_fix = jnp.any(
            is_pct & (pos >= auth_start[:, None]) & (pos < at[:, None]),
            axis=1,
        )
        # Only an actual >19-digit DIGITS port needs the oracle (the host
        # parses it with arbitrary precision); a non-digit port region of
        # any length is just registry-based.  A 19-digit port beyond
        # Long.MAX decodes on device and is demoted host-side by the
        # batch combine's overflow mask.
        abs_ok = (
            has_scheme & scheme_ok & dslash
            & ~(
                has_pcolon & ~port_empty & port_digits
                & (port_len > MAX_LONG_DIGITS)
            )
        )
    else:
        # Authority details (userinfo/host/port) are not requested: skip
        # their reductions.  Correct for path/query/protocol/ref because
        # the repair chain's %-insertions in the authority cannot change
        # the path/query SPAN CONTENTS (only shift the repaired copy), a
        # >19-digit port affects only the port parse, and registry-vs-
        # server validation affects only the authority outputs.
        false_v = jnp.zeros(B, dtype=bool)
        zero_v = jnp.zeros(B, dtype=jnp.int32)
        has_at = false_v
        at = rest_start = host_end = port_start = zero_v
        has_pcolon = port_empty = false_v
        registry = jnp.ones(B, dtype=bool)  # never deliver authority parts
        ui_fix = false_v
        abs_ok = has_scheme & scheme_ok & dslash
    is_abs = has_scheme & abs_ok & ~all_null
    # Opaque URIs (scheme but no '//': mailto:, urn:, news:): java.net.URI
    # leaves the authority None, so protocol + path (+ query past the
    # first separator) deliver and host/userinfo/port are null
    # (HttpUriDissector.java:190-199 via the _URI_SPLIT no-authority arm).
    opaque = has_scheme & scheme_ok & ~dslash & ~all_null
    # Scheme-less, not starting with '/': no authority possible — the whole
    # head is path (protocol/userinfo/host/port null).
    case3 = (~has_scheme) & (~relative) & (~all_null)
    handled = all_null | relative | case3 | is_abs | opaque
    ok = clean & handled

    zero_span = start
    show_auth = is_abs & ~registry
    path_begin = jnp.where(
        is_abs, auth_end, jnp.where(opaque, first_colon + 1, start)
    )
    path_fix = jnp.any(
        is_pct & (pos >= path_begin[:, None]) & (pos < first_sep[:, None]),
        axis=1,
    )
    # Query rows change under the host chain when they hold a bad escape
    # (repaired to %25) OR an encode-set byte (delivered %-ESCAPED — the
    # query, unlike the path, is never percent-decoded).
    query_fix = jnp.any(
        (pct_bad | enc) & (pos >= first_sep[:, None]), axis=1
    )
    has_query = (~all_null) & (first_sep < end)

    def span(show, s, e):
        return jnp.where(show, s, zero_span), jnp.where(show, e, zero_span)

    proto_s, proto_e = span(is_abs | opaque, start, first_colon)
    ui_show = show_auth & has_at
    ui_s, ui_e = span(ui_show, auth_start, at)
    host_s, host_e = span(show_auth, rest_start, host_end)
    port_show = show_auth & has_pcolon & ~port_empty
    port_s, port_e = span(port_show, port_start, auth_end)
    return {
        "ok": ok,
        "overflow": jnp.zeros(B, dtype=bool),
        "all_null": all_null,
        "path_start": jnp.where(all_null, zero_span, path_begin),
        "path_end": jnp.where(all_null, zero_span, jnp.maximum(first_sep, path_begin)),
        "path_null": all_null,
        "query_start": jnp.where(all_null, zero_span, first_sep),
        "query_end": jnp.where(all_null, zero_span, end),
        "query_null": all_null,
        "query_amp": has_query,
        "proto_start": proto_s,
        "proto_end": proto_e,
        "proto_null": all_null | ~(is_abs | opaque),
        "userinfo_start": ui_s,
        "userinfo_end": ui_e,
        "userinfo_null": all_null | ~ui_show,
        "userinfo_fix": ui_fix & ui_show,
        "host_start": host_s,
        "host_end": host_e,
        "host_null": all_null | ~show_auth,
        "port_start": port_s,
        "port_end": port_e,
        "path_fix": path_fix,
        "query_fix": query_fix,
    }


def parse_ipv4_spans(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dotted-quad spans -> (u32, ok, has_colon).

    Mirrors ``ipaddress.ip_address`` strictness for IPv4 (exactly four
    octets, 0-255, no leading zeros — AbstractGeoIPDissector parses with it
    and silently delivers nothing on failure).  ``has_colon`` flags spans
    that look like IPv6 literals: the host DOES look those up, so the
    caller routes them to the oracle instead of treating them as misses.
    """
    extract = extract or gather_span_bytes
    B = buf.shape[0]
    MAX_IP = 15  # 255.255.255.255
    b = extract(buf, start, MAX_IP)
    w = end - start

    octet = jnp.zeros(B, dtype=jnp.int32)
    ndig = jnp.zeros(B, dtype=jnp.int32)
    lead0 = jnp.zeros(B, dtype=bool)
    ndots = jnp.zeros(B, dtype=jnp.int32)
    value = jnp.zeros(B, dtype=jnp.uint32)
    good = jnp.ones(B, dtype=bool)
    has_colon = jnp.zeros(B, dtype=bool)
    for i in range(MAX_IP):
        in_span = i < w
        byte = b[:, i]
        has_colon = has_colon | (in_span & (byte == np.uint8(ord(":"))))
        d = (byte - np.uint8(ord("0"))).astype(jnp.int32)
        is_digit = (d >= 0) & (d <= 9)
        is_dot = byte == np.uint8(ord("."))
        # Leading zero: an octet whose first digit is 0 and has more digits.
        lead0 = lead0 | (in_span & is_digit & (ndig == 1) & (octet == 0))
        octet = jnp.where(in_span & is_digit, octet * 10 + d, octet)
        ndig = jnp.where(in_span & is_digit, ndig + 1, ndig)
        good = good & (~in_span | is_digit | is_dot)
        good = good & ~(in_span & (octet > 255))
        close = in_span & is_dot
        good = good & ~(close & (ndig == 0))
        value = jnp.where(
            close, (value << 8) | octet.astype(jnp.uint32), value
        )
        ndots = jnp.where(close, ndots + 1, ndots)
        octet = jnp.where(close, 0, octet)
        ndig = jnp.where(close, 0, ndig)
    value = (value << 8) | octet.astype(jnp.uint32)
    ok = (
        good
        & (w >= 7) & (w <= MAX_IP)
        & (ndots == 3)
        & (ndig > 0)           # final octet non-empty
        & ~lead0
    )
    return value, ok, has_colon


@functools.lru_cache(maxsize=None)
def _csr_class_table(
    sep_byte: Optional[int], kv: int, uri_encoded: bool
) -> np.ndarray:
    """256-entry byte-class table for split_csr: bit 0 = value-decode
    trigger (%/+ and, uri_encoded, the printable encode set), bit 1 =
    name-escape trigger (% / encode set), bit 2 = high byte, bit 3 = the
    kv byte, bit 4 = a single-byte separator.  One gather through this
    table replaces ~20 per-byte compare/or passes over the span."""
    t = np.zeros(256, dtype=np.uint8)
    t[ord("%")] |= 1 | 2
    t[ord("+")] |= 1
    t[0x80:] |= 4
    if uri_encoded:
        from ..dissectors.uri import ENCODE_PRINTABLE

        for ch in ENCODE_PRINTABLE:
            t[ch] |= 1 | 2
    t[kv] |= 8
    if sep_byte is not None:
        t[sep_byte] |= 16
    return t


def split_csr(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    max_segments: int,
    sep: bytes = b"&",
    kv: int = ord("="),
    uri_encoded: bool = False,
    window: Optional[int] = None,
) -> Dict[str, object]:
    """CSR segment split of spans on device: the vectorized core of the
    wildcard dissectors (QueryStringFieldDissector.java:76-108 splits on
    ``&`` then ``=``; cookies split on the two-byte ``"; "`` then ``=``).

    Locates up to ``max_segments`` separator-delimited segments per line and,
    per segment, the first ``kv`` byte.  Returns per-segment arrays (lists of
    [B] vectors) — segment k spans [seg_start[k], seg_end[k]); name/value
    split at eq_pos[k] (== seg_end[k] when no kv byte).  ``decode[k]`` marks
    values containing ``%`` or ``+`` (host applies resilientUrlDecode to
    exactly those).  ``overflow`` marks lines with more segments than slots —
    the caller routes them to the oracle.

    Empty segments keep their slot (the host skips them at materialization);
    compaction on a SIMD machine would cost a sort, skipping on host costs
    nothing.

    ``window`` bounds the scan domain: the span bytes are gathered into a
    compact [B, window] buffer and every [.,L]-wide plane above shrinks to
    [., window] — the scans are the kernel cost, and spans (query strings,
    cookie headers) are tiny next to the padded line length.  Rows whose
    span exceeds the window raise ``overflow`` — the same exact capacity
    defer as running out of slots, and the caller's adaptive response
    (double the slots, which callers scale the window by) resolves both.
    Windowed outputs are bit-identical to the unwindowed split for every
    row that fits: the core sees the same span bytes at a rebased origin.
    """
    B, L = buf.shape
    if window is not None and int(window) < L:
        W = int(window)
        span = end - start
        widx = jnp.clip(
            start[:, None] + jax.lax.broadcasted_iota(jnp.int32, (1, W), 1),
            0, L - 1,
        )
        wbuf = jnp.take_along_axis(buf, jnp.broadcast_to(widx, (B, W)), axis=1)
        res = split_csr(
            wbuf,
            jnp.zeros_like(start),
            jnp.minimum(span, W),
            max_segments,
            sep=sep,
            kv=kv,
            uri_encoded=uri_encoded,
        )
        for name in ("seg_start", "seg_end", "eq_pos"):
            res[name] = [v + start for v in res[name]]
        res["overflow"] = res["overflow"] | (span > W)
        return res
    n_sep = len(sep)
    shift = shift_zero
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])
    # Byte classes via ONE table gather (uri_encoded folds the printable
    # encode set into the dec/pct planes: query strings reach the host
    # dissector AFTER the URI encode step, so segments holding encode-set
    # bytes differ from the raw device span — names stay
    # %-escaped-and-lowercased, values escape then resilient-decode —
    # and take the per-row path alongside %/+).
    cls = jnp.asarray(
        _csr_class_table(sep[0] if n_sep == 1 else None, kv, uri_encoded)
    )[buf]
    if n_sep == 1:
        is_sep = (cls & 16) != 0
    else:
        is_sep = None
        for k, byte in enumerate(sep):
            part = (
                shift(buf, k) == np.uint8(byte) if k else (buf == np.uint8(byte))
            )
            is_sep = part if is_sep is None else (is_sep & part)
    is_sep = is_sep & in_span & (pos + n_sep <= end[:, None])
    is_kv = ((cls & 8) != 0) & in_span
    is_dec = ((cls & 1) != 0) & in_span
    is_pct = ((cls & 2) != 0) & in_span
    is_high = ((cls & 4) != 0) & in_span

    # Slot-invariant precomputation (the round-20 restructure): the
    # original per-slot scans rebuilt ~16 [B, L] masks + reductions per
    # slot (256 full-array passes at 16 slots — the dominant kernel cost
    # once concrete query keys made CSR groups routine).  Every per-slot
    # quantity is a "first occurrence at/after cursor" or a "count in a
    # sub-range", so ONE suffix-min per occurrence plane and ONE
    # exclusive prefix-count per flag plane replace them; each slot then
    # costs a handful of [B]-sized gathers.  Outputs are bit-identical
    # to the sequential scan by construction.
    masked_sep = jnp.where(is_sep, pos, L)
    suffix_sep = jax.lax.cummin(masked_sep, axis=1, reverse=True)
    masked_kv = jnp.where(is_kv, pos, L)
    suffix_kv = jax.lax.cummin(masked_kv, axis=1, reverse=True)

    def _excount(m):
        # c[:, i] = occurrences in [0, i) — exclusive prefix count.
        c = jnp.cumsum(m.astype(jnp.int32), axis=1)
        return jnp.pad(c, ((0, 0), (1, 0)))

    # The three flag planes pack into ONE scan when per-plane counts fit
    # 10 bits (always true under a window): every field of the packed
    # exclusive count is non-decreasing, so field-wise differences cannot
    # borrow across fields — one cumsum + four gathers replace three + six.
    packed = None
    if L < 1024:
        packed = _excount(
            is_dec.astype(jnp.int32)
            | (is_pct.astype(jnp.int32) << 10)
            | (is_high.astype(jnp.int32) << 20)
        )
    else:
        cum_dec = _excount(is_dec)
        cum_pct = _excount(is_pct)
        cum_high = _excount(is_high)

    def _gat(mat, idx, fill, width):
        v = jnp.take_along_axis(
            mat, jnp.clip(idx, 0, width - 1)[:, None], axis=1
        )[:, 0]
        return jnp.where(idx >= width, fill, v)

    seg_start: list = []
    seg_end: list = []
    eq_pos: list = []
    decode: list = []
    name_pct: list = []
    name_high: list = []
    cursor = start
    for _ in range(max_segments):
        # First separator at/after cursor; first kv byte at/after cursor
        # clamped into the segment (kv bytes of earlier segments are all
        # below cursor — it advances past each terminator).
        nxt = _gat(suffix_sep, cursor, L, L)
        s_end = jnp.minimum(nxt, end)
        eq = jnp.minimum(_gat(suffix_kv, cursor, L, L), s_end)
        # decode: any %/+ in the value range (eq, s_end); name flags:
        # any %-ish / high byte in the name range [cursor, eq).  Range
        # bounds are clamped so empty/trailing slots count zero.
        if packed is not None:
            val_d = (
                _gat(packed, s_end, 0, L + 1)
                - _gat(packed, jnp.minimum(eq + 1, s_end), 0, L + 1)
            )
            nam_d = (
                _gat(packed, eq, 0, L + 1)
                - _gat(packed, jnp.minimum(cursor, eq), 0, L + 1)
            )
            dec_cnt = val_d & 0x3FF
            np_cnt = (nam_d >> 10) & 0x3FF
            nh_cnt = nam_d >> 20
        else:
            dec_cnt = (
                _gat(cum_dec, s_end, 0, L + 1)
                - _gat(cum_dec, jnp.minimum(eq + 1, s_end), 0, L + 1)
            )
            np_cnt = (
                _gat(cum_pct, eq, 0, L + 1)
                - _gat(cum_pct, jnp.minimum(cursor, eq), 0, L + 1)
            )
            nh_cnt = (
                _gat(cum_high, eq, 0, L + 1)
                - _gat(cum_high, jnp.minimum(cursor, eq), 0, L + 1)
            )
        seg_start.append(cursor)
        seg_end.append(s_end)
        eq_pos.append(eq)
        decode.append(dec_cnt > 0)
        name_pct.append(np_cnt > 0)
        name_high.append(nh_cnt > 0)
        cursor = s_end + n_sep
    # One more separator past the last slot = segments we cannot ship.
    has_more = (_gat(suffix_sep, cursor, L, L) < L) | (cursor < end)
    return {
        "seg_start": seg_start,
        "seg_end": seg_end,
        "eq_pos": eq_pos,
        "decode": decode,
        "name_pct": name_pct,
        "name_high": name_high,
        "overflow": has_more,
    }


def _ci_literal_mask(buf, shift, lit: bytes, in_span):
    """[B, L] bool: case-insensitive `lit` match starting at this position
    (ASCII fold on letters only)."""
    m = None
    for k, ch in enumerate(lit):
        col = shift(buf, k) if k else buf
        if ord("a") <= ch <= ord("z"):
            part = (col | np.uint8(0x20)) == np.uint8(ch)
        else:
            part = col == np.uint8(ch)
        m = part if m is None else (m & part)
    return m & in_span


def split_setcookie_csr(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    max_segments: int,
) -> Dict[str, object]:
    """Device split of a Set-Cookie response header list: ``", "`` separated
    cookies with the expires-comma rejoin quirk
    (ResponseSetCookieListDissector.java:78-115, dissectors/cookies.py:120).

    A part whose FIRST (case-insensitive) ``expires=`` starts within 15
    bytes of its end is glued to the following part (the expires date
    itself contains ``", "``); the glued part is NOT re-checked.  Host
    quirks preserved exactly: a trailing held part is silently dropped
    (``emit`` False); a held part followed by another holding part is
    overwritten on the host — those rows (and parts starting with a
    case-insensitive ``set-cookie`` prefix, which the host name parser
    strips) set ``bad`` and take the oracle.

    Per segment k: the cookie name spans [seg_start[k], name_end[k])
    (host strips + lowercases it; empty names are skipped there), the
    delivered value is the RAW whole segment [seg_start[k], seg_end[k]).
    ``overflow`` marks lines with more cookies than slots.
    """
    # The shared quirk constant (len("expires=XXXXXXX")) — imported from
    # the host dissector so device and host can never diverge.
    from ..dissectors.cookies import _MINIMAL_EXPIRES_LENGTH

    B, L = buf.shape
    shift = shift_zero
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])

    is_sep = (
        (buf == np.uint8(ord(",")))
        & (shift(buf, 1) == np.uint8(ord(" ")))
        & in_span
        & (pos + 2 <= end[:, None])
    )
    is_semi = (buf == np.uint8(ord(";"))) & in_span
    is_eq = (buf == np.uint8(ord("="))) & in_span
    exp_mask = _ci_literal_mask(buf, shift, b"expires=", in_span)
    prefix_mask = _ci_literal_mask(buf, shift, b"set-cookie", in_span)

    seg_start: list = []
    seg_end_l: list = []
    name_end_l: list = []
    emit_l: list = []
    bad = jnp.zeros(B, dtype=bool)
    cursor = start
    for _ in range(max_segments):
        usable = is_sep & (pos >= cursor[:, None])
        nxt = jnp.min(jnp.where(usable, pos, L), axis=1).astype(jnp.int32)
        s_end = jnp.minimum(nxt, end)
        exp_usable = exp_mask & (pos >= cursor[:, None]) & (
            pos + 8 <= s_end[:, None]
        )
        exp = jnp.min(jnp.where(exp_usable, pos, L), axis=1).astype(jnp.int32)
        hold = (exp < L) & (exp > s_end - _MINIMAL_EXPIRES_LENGTH)
        last = s_end >= end

        # Merged end: the separator after the held part's date fragment.
        usable2 = is_sep & (pos >= (s_end + 2)[:, None])
        nxt2 = jnp.min(jnp.where(usable2, pos, L), axis=1).astype(jnp.int32)
        s_end2 = jnp.minimum(nxt2, end)
        exp2_usable = exp_mask & (pos >= (s_end + 2)[:, None]) & (
            pos + 8 <= s_end2[:, None]
        )
        exp2 = jnp.min(jnp.where(exp2_usable, pos, L), axis=1).astype(jnp.int32)
        hold2 = (exp2 < L) & (exp2 > s_end2 - _MINIMAL_EXPIRES_LENGTH)

        merged = hold & ~last
        bad = bad | (merged & hold2)  # host overwrite quirk -> oracle
        drop = hold & last            # trailing held part: host drops it
        seg_e = jnp.where(merged, s_end2, s_end)

        semi = jnp.min(
            jnp.where(is_semi & (pos >= cursor[:, None]) & (pos < seg_e[:, None]),
                      pos, L),
            axis=1,
        ).astype(jnp.int32)
        eq_bound = jnp.minimum(semi, seg_e)
        eq = jnp.min(
            jnp.where(is_eq & (pos >= cursor[:, None]) & (pos < eq_bound[:, None]),
                      pos, L),
            axis=1,
        ).astype(jnp.int32)
        name_end = jnp.minimum(jnp.minimum(eq, semi), seg_e)
        nonempty = cursor < seg_e
        emit = nonempty & ~drop
        # The host name parser strips a (case-insensitive) set-cookie[2]:
        # prefix first — those rows go to the oracle.
        has_prefix = jnp.any(
            prefix_mask & (pos == cursor[:, None]), axis=1
        )
        bad = bad | (emit & has_prefix)

        seg_start.append(cursor)
        seg_end_l.append(seg_e)
        name_end_l.append(name_end)
        emit_l.append(emit)
        cursor = seg_e + 2
    usable = is_sep & (pos >= cursor[:, None])
    has_more = jnp.any(usable, axis=1) | (cursor < end)
    return {
        "seg_start": seg_start,
        "seg_end": seg_end_l,
        "name_end": name_end_l,
        "emit": emit_l,
        "bad": bad,
        "overflow": has_more,
    }


def parse_mod_unique_id(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """mod_unique_id spans -> decoded u32 words, vectorized.

    The host decoder (dissectors/mod_unique_id.py) delivers iff the token
    is EXACTLY 24 chars of ``[A-Za-z0-9_-]`` (any other byte — incl. the
    '@'-mapped '+'/'/' — is skipped by the lenient base64 decoder, leaving
    fewer than 18 bytes, so nothing is delivered).  24 chars x 6 bits =
    exactly 18 bytes: 32-bit epoch-seconds, 32-bit IPv4, 32-bit pid,
    16-bit counter, 32-bit thread index.

    Returns ({"time","ip","pid","counter","thread"}, ok): the u32 words
    bitcast to int32 (host re-widens with ``& 0xFFFFFFFF``), counter as a
    plain int32.
    """
    extract = extract or gather_span_bytes
    b = extract(buf, start, 24)
    w = end - start

    is_upper = (b >= np.uint8(ord("A"))) & (b <= np.uint8(ord("Z")))
    is_lower = (b >= np.uint8(ord("a"))) & (b <= np.uint8(ord("z")))
    is_digit = (b >= np.uint8(ord("0"))) & (b <= np.uint8(ord("9")))
    is_dash = b == np.uint8(ord("-"))
    is_under = b == np.uint8(ord("_"))
    ok = (w == 24) & jnp.all(
        is_upper | is_lower | is_digit | is_dash | is_under, axis=1
    )

    b32 = b.astype(jnp.int32)
    v = jnp.where(
        is_upper, b32 - ord("A"),
        jnp.where(
            is_lower, b32 - ord("a") + 26,
            jnp.where(
                is_digit, b32 - ord("0") + 52,
                jnp.where(is_dash, 62, 63),  # '-' -> '+', '_' -> '/'
            ),
        ),
    ).astype(jnp.uint32)

    # 4 chars -> one 24-bit group; 6 groups -> the 18 decoded bytes.
    g = [
        (v[:, i] << 18) | (v[:, i + 1] << 12) | (v[:, i + 2] << 6) | v[:, i + 3]
        for i in range(0, 24, 4)
    ]
    time_u = (g[0] << 8) | (g[1] >> 16)
    ip_u = ((g[1] & 0xFFFF) << 16) | (g[2] >> 8)
    pid_u = ((g[2] & 0xFF) << 24) | g[3]
    counter = (g[4] >> 8).astype(jnp.int32)
    thread_u = ((g[4] & 0xFF) << 24) | g[5]

    def cast(x):
        return jax.lax.bitcast_convert_type(x, jnp.int32)

    return (
        {
            "time": cast(time_u),
            "ip": cast(ip_u),
            "pid": cast(pid_u),
            "counter": counter,
            "thread": cast(thread_u),
        },
        ok,
    )


def split_firstline(
    buf: jnp.ndarray,
    lengths: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    extract=None,
) -> Dict[str, jnp.ndarray]:
    """"METHOD URI PROTO" span -> method/uri/protocol sub-spans.

    Mirrors HttpFirstLineDissector: method = up to the first space, protocol =
    after the last space (only when it matches ``xxx/d.d`` shape — otherwise
    the truncated-line fallback applies: protocol absent, uri to the end).
    ``has_protocol`` distinguishes the two cases; fully garbage lines (no
    space at all) get ok=False.
    """
    extract = extract or gather_span_bytes
    B, L = buf.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])
    is_space = (buf == np.uint8(ord(" "))) & in_span

    first_space = jnp.min(jnp.where(is_space, pos, L), axis=1)
    last_space = jnp.max(jnp.where(is_space, pos, -1), axis=1)

    has_space = first_space < L
    method_start = start
    method_end = jnp.where(has_space, first_space, start)

    # Protocol candidate: after the last space; valid only when it matches
    # HTTP/[0-9]+\.[0-9]+ exactly (the 3-part regex arm; otherwise the
    # truncated-line fallback applies).
    proto_start = jnp.where(has_space, last_space + 1, end)
    head = extract(buf, proto_start, 5)
    head_ok = (
        (head[:, 0] == np.uint8(ord("H")))
        & (head[:, 1] == np.uint8(ord("T")))
        & (head[:, 2] == np.uint8(ord("T")))
        & (head[:, 3] == np.uint8(ord("P")))
        & (head[:, 4] == np.uint8(ord("/")))
    )
    ver = (pos >= (proto_start + 5)[:, None]) & (pos < end[:, None])
    is_digit = (buf >= np.uint8(ord("0"))) & (buf <= np.uint8(ord("9")))
    is_dot = buf == np.uint8(ord("."))
    ver_chars_ok = jnp.all(is_digit | is_dot | ~ver, axis=1)
    one_dot = jnp.sum(jnp.where(is_dot & ver, 1, 0), axis=1) == 1
    last_b = extract(buf, jnp.maximum(end - 1, 0), 1)[:, 0]
    first_ver = extract(buf, proto_start + 5, 1)[:, 0]
    ver_ok = (
        ((end - proto_start) >= 8)
        & ver_chars_ok
        & one_dot
        & (first_ver >= np.uint8(ord("0"))) & (first_ver <= np.uint8(ord("9")))
        & (last_b >= np.uint8(ord("0"))) & (last_b <= np.uint8(ord("9")))
    )
    has_protocol = has_space & (last_space > first_space) & head_ok & ver_ok

    uri_start = jnp.where(has_space, first_space + 1, end)
    uri_end = jnp.where(has_protocol, last_space, end)

    return {
        "method_start": method_start,
        "method_end": method_end,
        "uri_start": uri_start,
        "uri_end": uri_end,
        "proto_start": jnp.where(has_protocol, proto_start, end),
        "proto_end": end,
        "has_protocol": has_protocol,
        "ok": has_space,
    }


def split_protocol_version(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    dash: Optional[jnp.ndarray] = None,
) -> Dict[str, jnp.ndarray]:
    """HTTP.PROTOCOL_VERSION value span ("HTTP/1.1") -> protocol + version.

    Mirrors HttpFirstLineProtocolDissector exactly: a ``None``/``""``/``"-"``
    input delivers nothing (``dash`` carries the direct-token CLF null;
    sub-span chains never produce a lone dash); a value without ``/``
    delivers explicit nulls for both outputs; otherwise protocol is
    everything before the FIRST ``/`` (``value.split("/", 1)``) and version
    everything after it — either side may be the empty string.
    """
    B, L = buf.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, L), 1)
    in_span = (pos >= start[:, None]) & (pos < end[:, None])
    slash = jnp.min(
        jnp.where((buf == np.uint8(ord("/"))) & in_span, pos, L), axis=1
    )
    absent = start >= end
    if dash is not None:
        absent = absent | dash
    return {
        "proto_start": start,
        "proto_end": jnp.minimum(slash, end),
        "ver_start": jnp.minimum(slash + 1, end),
        "ver_end": end,
        "null": absent | (slash >= L),
    }


def unescape_compact_spans(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    width: int,
    extract=None,
):
    """Device-side inverse of Apache's ``ap_escape_logitem`` for the
    byte-DROPPING escape classes: ``\\"`` -> ``"`` and ``\\\\`` -> ``\\``
    inside a quoted-field span, as a compaction gather.

    Returns ``(out, out_len, exact)``: ``out`` is ``[B, width]`` span
    bytes with every ESCAPING backslash removed (bytes past ``out_len``
    zeroed), ``exact`` marks rows where this IS the reference decode
    (:func:`...dissectors.utils.decode_apache_httpd_log_value`) of the
    span.  Rows carrying a byte-SUBSTITUTING escape (``\\n``/``\\t``/
    ``\\xhh`` ... — C-escapes the reference maps to different bytes, and
    a bare trailing backslash, which it raises on) or a span longer than
    ``width`` are flagged inexact and left for the host decoder.

    NOTE this pass is NOT in the product delivery path: the reference
    compares the VALUE (not the token name) before applying its decode
    (ApacheHttpdLogFormatDissector.java:170-198 — see
    httpd/utils_apache.py), so the observable host semantics deliver
    quoted-field values VERBATIM, backslashes included, and the
    escape-parity split (pipeline.compute_split) already emits exactly
    those verbatim spans.  The pass exists for consumers that want the
    DECODED form on device (and as the executable spec of the escape
    geometry, differentially locked against the reference decoder in
    tests/test_fuzz_differential.py).  Cost: one [B, width] stable
    argsort — a cold-path utility, not a hot-path stage.

    Decode model (mirrors the reference's left-to-right pair scan): in a
    maximal backslash run of length n, the backslashes at even offsets
    0, 2, ... are the escaping bytes of ``\\\\`` pairs and are dropped;
    an odd run's trailing backslash is dropped only when it escapes a
    quote (``\\"``), kept verbatim before an unknown character (the
    reference's fall-through appends both bytes), and inexact before a
    substituting C-escape."""
    extract = extract or gather_span_bytes
    B = start.shape[0]
    width = min(width, buf.shape[1])  # extract clamps to the buffer width
    n = jnp.clip(end - start, 0, None)
    win = extract(buf, start, width).astype(jnp.int32)
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, width), 1)
    in_span = pos < n[:, None]
    is_bs = (win == ord("\\")) & in_span

    # Offset of each backslash inside its maximal run: distance to the
    # last non-backslash before it (same running-max derivation as
    # pipeline.escaped_lead_positions, here within the span window).
    non_bs = ~is_bs
    last_non_bs = jax.lax.cummax(jnp.where(non_bs, pos, -1), axis=1)
    prev_last = jnp.concatenate(
        [jnp.full((B, 1), -1, dtype=jnp.int32), last_non_bs[:, :-1]],
        axis=1,
    )
    even_offset = ((pos - prev_last) & 1) == 1  # offset = pos-prev_last-1

    nxt = shift_zero(win, 1)
    nxt_in_span = (pos + 1) < n[:, None]
    last_of_run = is_bs & ~(shift_zero(is_bs, 1) & nxt_in_span)
    odd_tail = last_of_run & even_offset  # odd run length <=> last offset even
    escapes_quote = odd_tail & nxt_in_span & (nxt == ord('"'))
    # Substituting C-escapes (byte rewrite, not a drop) and a trailing
    # backslash with nothing after it: the reference decode diverges
    # from pure compaction there — flag the row.
    subst = jnp.zeros(odd_tail.shape, dtype=bool)
    for c in b"bnrtvx":
        subst = subst | (nxt == c)
    inexact_pos = odd_tail & ((subst & nxt_in_span) | ~nxt_in_span)

    drop = is_bs & even_offset & (~odd_tail | escapes_quote)
    keep = in_span & ~drop
    out_len = jnp.sum(jnp.where(keep, 1, 0), axis=1)
    # Stable compaction: kept bytes first, original order preserved.
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    out = jnp.take_along_axis(win, order, axis=1)
    out = jnp.where(pos < out_len[:, None], out, 0)
    exact = (n <= width) & ~jnp.any(inexact_pos, axis=1)
    return out, out_len, exact
