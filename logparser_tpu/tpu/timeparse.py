"""Device-side generic fixed-layout timestamp parsing.

The host engine compiles every timestamp pattern (java.time subset or
strftime) into a :class:`~logparser_tpu.dissectors.timelayout.TimeLayout` —
a flat item list.  This module compiles the *fixed-width subset* of those
layouts one step further, into a :class:`DeviceTimeLayout` whose every item
sits at a static byte offset, and executes it over ``[B]`` spans of a
``[B, L]`` byte batch as pure vector arithmetic (the TPU replacement for
TimeStampDissector.java:404-424's per-line ``DateTimeFormatter.parse``).

Device-compilable layouts: numeric fields with min==max width, literals,
month/day NAME tables (short or full, any locale — entries are matched
byte-wise against the layout's locale tables, so variable-width localized
names like French ``janv.``/``août`` segment the layout at a per-row
cursor instead of forcing the oracle), am/pm, and at most one
variable-width UTC-offset in tail position (``ZZ`` accepts
``+HHMM``/``+HH:MM`` and ``XXX`` accepts ``Z``/``+HH:MM``; both are
distinguishable by remaining span width).  This covers the Apache default
``dd/MMM/yyyy:HH:mm:ss ZZ``, nginx ``$time_iso8601``
(``yyyy-MM-dd'T'HH:mm:ssXXX``), the fixed-width strftime family, and the
localized variants of all of these, plus %Z zone TEXT for the
fixed-offset abbreviation family (UTC/GMT/UT/Z).  DST zone names /
region ids (they need tzdata) and week-based dates stay on the host
oracle.

Validation discipline: the device must never accept a span the host layout
rejects (a false-accept would bypass the oracle with a wrong value).  Every
digit is range-checked, literals and ASCII name letters compare
case-insensitively exactly like ``TimeLayout.parse`` (non-ASCII name bytes
compare exactly — an off-case ``AOÛT`` fails device validation and falls
back to the oracle, which accepts it; device-stricter is always safe),
month/day names must be table members, and day-in-month honors leap years.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .postproc import pow10_weights
from ..dissectors.timelayout import LocaleData, TimeLayout

# Zones that are a fixed UTC offset year-round (no DST), so a layout whose
# default_zone is one of these still compiles to constant offset arithmetic.
_FIXED_OFFSET_ZONES = {"UTC": 0, "GMT": 0, "Z": 0, "UT": 0, "Etc/UTC": 0}


@dataclass(frozen=True)
class _DevItem:
    kind: str        # lit | num | name | ampm | zone
    offset: int      # byte offset within its SEGMENT
    width: int       # fixed width (for name/ampm/zone: max entry width)
    field: str = ""  # num: layout field; name: "month" | "dayofweek"
    text: bytes = b""            # lit
    table: Tuple[bytes, ...] = ()  # name/ampm/zone: per-entry bytes
    # zone only: per-entry index into the layout's ZoneDeviceTable
    # (parallel to `table`) + whether the entry matches case-insensitively
    # (abbreviations do, region ids are exact like zoneinfo's file paths).
    zone_idx: Tuple[int, ...] = ()
    fold_flags: Tuple[bool, ...] = ()


@dataclass
class DeviceTimeLayout:
    """A TimeLayout resolved to per-segment byte offsets.

    Segments are runs of fixed-width items; a NAME item whose locale
    table has entries of differing byte lengths forms its own segment
    and advances the per-row cursor by the matched entry's length —
    that is how localized month names (French ``mars`` vs ``janv.``)
    stay device-resident."""

    segments: Tuple[Tuple[_DevItem, ...], ...]
    seg_widths: Tuple[int, ...]    # fixed byte width per segment; -1 = var
    tail: str                      # "" | "offset" | "offset_colon"
    default_offset_seconds: int    # applied when tail == ""
    locale: Optional[LocaleData] = None
    min_prefix: int = 0            # lower bound of the pre-tail width
    # zonetext layouts: the tzdata transition tables the matched zone
    # index resolves through (dissectors/tztable.py).
    zone_table: Optional[object] = None


# Numeric layout fields the device models, with their post-parse range
# checks applied in parse_device_timestamp.
_NUM_FIELDS = {
    "year", "year2", "month", "day", "hour", "clock_hour", "hour12",
    "minute", "second", "milli",
}


def compile_layout_for_device(layout: TimeLayout) -> Optional[DeviceTimeLayout]:
    """TimeLayout -> DeviceTimeLayout, or None when any item is outside the
    device subset (caller keeps the field on the host oracle)."""
    loc = layout.locale
    segments: List[Tuple[_DevItem, ...]] = []
    seg_widths: List[int] = []
    cur: List[_DevItem] = []
    offset = 0
    min_prefix = 0
    tail = ""
    n = len(layout.items)

    def close_segment():
        nonlocal cur, offset
        if cur:
            segments.append(tuple(cur))
            seg_widths.append(offset)
        cur = []
        offset = 0

    def name_tables(field: str, style: str):
        if field == "monthname":
            names = loc.months_full if style == "full" else loc.months_short
            return "month", names
        if field == "dayname":
            names = loc.days_full if style == "full" else loc.days_short
            return "dayofweek", names
        return "ampm", list(loc.ampm)

    for idx, it in enumerate(layout.items):
        kind = it[0]
        if kind == "lit":
            text = it[1].encode("utf-8", errors="strict")
            cur.append(_DevItem("lit", offset, len(text), text=text))
            offset += len(text)
            min_prefix += len(text)
        elif kind == "num":
            _, field, minw, maxw, space_pad = it
            if space_pad or minw != maxw or field not in _NUM_FIELDS:
                return None
            cur.append(_DevItem("num", offset, minw, field=field))
            offset += minw
            min_prefix += minw
        elif kind == "text":
            _, field, style = it
            key, names = name_tables(field, style)
            table = tuple(nm.encode("utf-8") for nm in names)
            widths = {len(t) for t in table}
            w = max(widths)
            dev_kind = "ampm" if key == "ampm" else "name"
            if len(widths) == 1:
                cur.append(_DevItem(dev_kind, offset, w, field=key,
                                    table=table))
                offset += w
                min_prefix += w
            else:
                # Variable entry widths: own segment, per-row advance.
                close_segment()
                segments.append(
                    (_DevItem(dev_kind, 0, w, field=key, table=table),)
                )
                seg_widths.append(-1)
                min_prefix += min(widths)
        elif kind in ("offset", "offset_colon"):
            if idx != n - 1:
                return None  # variable width is only decodable at the tail
            tail = kind
        elif kind == "zonetext":
            # %Z zone TEXT, resolved on device through tzdata transition
            # tables (dissectors/tztable.py; the TPU analogue of
            # TimeStampDissector.java:404-424's java.time zone
            # resolution): abbreviations match case-insensitively and map
            # through the host's own _ZONE_ABBREVIATIONS table, region
            # ids match byte-exactly (zoneinfo paths are case-sensitive).
            # Rows with zones outside the device vocabulary — or wall
            # times outside a zone's exact window — fail device
            # validation and take the oracle, which resolves identically
            # through zoneinfo.  The host consumes the zone token
            # GREEDILY over [A-Za-z0-9_/+-], so the match also checks the
            # byte AFTER the entry is outside that class ("UTCX" must not
            # device-accept as UTC) — the +1 width gives the peek byte.
            from ..dissectors.timelayout import _ZONE_ABBREVIATIONS
            from ..dissectors.tztable import default_zone_table

            ztab = default_zone_table()
            zone_of = {name: i for i, name in enumerate(ztab.zones)}
            entries: List[Tuple[bytes, int, bool]] = []
            # Abbreviations first: the host checks its abbreviation table
            # before treating the token as a region id.
            for abbr, target in _ZONE_ABBREVIATIONS.items():
                zi = zone_of.get(target)
                if zi is not None:
                    entries.append((abbr.encode(), zi, True))
            for name, zi in zone_of.items():
                entries.append((name.encode(), zi, False))
            if not entries:
                # No usable tzdata on this host (empty vocabulary):
                # %Z layouts stay host-only instead of crashing compile.
                return None
            table = tuple(e[0] for e in entries)
            close_segment()
            segments.append((
                _DevItem(
                    "zone", 0, max(len(t) for t in table) + 1,
                    field="zone", table=table,
                    zone_idx=tuple(e[1] for e in entries),
                    fold_flags=tuple(e[2] for e in entries),
                ),
            ))
            seg_widths.append(-1)
            min_prefix += min(len(t) for t in table)
        else:  # anything new: host-only
            return None
    close_segment()

    has_zone_item = any(
        i.kind == "zone" for seg in segments for i in seg
    )
    default_offset = 0
    if not tail and not has_zone_item:
        # (A zonetext item always supplies the zone, so default_zone is
        # dead for those layouts — no reason to reject a DST default.)
        zone = layout.default_zone
        if zone is not None and zone not in _FIXED_OFFSET_ZONES:
            return None  # DST zones need tzdata; host-only
        default_offset = _FIXED_OFFSET_ZONES.get(zone or "UTC", 0)

    flat = [i for seg in segments for i in seg]
    fields = {i.field for i in flat if i.kind == "num"}
    has_month = "month" in fields or any(
        i.kind == "name" and i.field == "month" for i in flat
    )
    if not ((("year" in fields) or ("year2" in fields)) and has_month
            and "day" in fields):
        return None  # incomplete date resolves through host paths
    zone_table = None
    if has_zone_item:
        from ..dissectors.tztable import default_zone_table

        zone_table = default_zone_table()
    return DeviceTimeLayout(
        tuple(segments), tuple(seg_widths), tail, default_offset,
        locale=loc, min_prefix=min_prefix, zone_table=zone_table,
    )


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------


def _fold_byte(byte: int) -> Optional[int]:
    """ASCII-lowercased byte value, or None for non-letters (compared
    exactly)."""
    if ord("a") <= (byte | 0x20) <= ord("z"):
        return byte | 0x20
    return None


def parse_device_timestamp(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    dl: DeviceTimeLayout,
    extract,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Execute a DeviceTimeLayout over [B] spans.

    Returns (components, ok): components has int32 arrays
    ``year month day hour minute second milli offset_seconds`` (local wall
    clock + UTC offset; epoch math happens host-side in int64).  Segments
    run at a per-row cursor, so variable-width localized name tables keep
    their rows on device.
    """
    B = buf.shape[0]
    width = end - start
    ok = width >= dl.min_prefix
    cursor = start

    zeros = jnp.zeros(B, dtype=jnp.int32)
    comp: Dict[str, jnp.ndarray] = {}

    def make_digits(win):
        # One [B, w] vector op chain instead of w scalar rounds.
        def digits(off: int, w: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
            d = (win[:, off : off + w] - np.uint8(ord("0"))).astype(jnp.int32)
            good = jnp.all((d >= 0) & (d <= 9), axis=1)
            val = jnp.sum(d * pow10_weights(w), axis=1).astype(jnp.int32)
            return val, good

        return digits

    def match_entry(b, lower, off: int, entry: bytes, fold: bool = True):
        m = None
        for i, byte in enumerate(entry):
            folded = _fold_byte(byte) if fold else None
            if folded is not None:
                part = lower[:, off + i] == np.uint8(folded)
            else:
                part = b[:, off + i] == np.uint8(byte)
            m = part if m is None else (m & part)
        return m if m is not None else jnp.ones(B, dtype=bool)

    # Single-segment fixed layouts (the common English shapes) extract
    # ONE window covering prefix + tail; segmented layouts pay one
    # extract per segment.  The merged window must FIT the buffer:
    # gather_span_bytes clamps its width to L, which would leave the
    # tail slice narrower than the 6 columns the tail parser indexes.
    one_shot = (
        len(dl.segments) == 1
        and dl.seg_widths
        and dl.seg_widths[0] >= 0
        and dl.seg_widths[0] + (6 if dl.tail else 0) <= buf.shape[1]
    )
    shared_win = None
    if one_shot:
        shared_win = extract(
            buf, cursor, dl.seg_widths[0] + (6 if dl.tail else 0)
        )

    month_from_name = None
    for seg, seg_w in zip(dl.segments, dl.seg_widths):
        if one_shot:
            b = shared_win
        else:
            win_w = seg_w if seg_w >= 0 else max(i.width for i in seg)
            b = extract(buf, cursor, win_w)
        lower = b | np.uint8(0x20)
        digits = make_digits(b)

        for it in seg:
            if it.kind == "lit":
                ok = ok & match_entry(b, lower, it.offset, it.text)
            elif it.kind == "num":
                val, good = digits(it.offset, it.width)
                ok = ok & good
                comp[it.field] = val
            elif it.kind in ("name", "ampm", "zone"):
                # Table match in host-table ORDER (first match wins, like
                # TimeLayout._parse_text): iterate reversed so earlier
                # entries overwrite later ones.
                value = zeros
                wsel = zeros
                matched = jnp.zeros(B, dtype=bool)
                for idx in reversed(range(len(it.table))):
                    entry = it.table[idx]
                    fold = (
                        it.fold_flags[idx]
                        if it.kind == "zone" and it.fold_flags else True
                    )
                    m = match_entry(b, lower, it.offset, entry, fold) & (
                        cursor + len(entry) <= end
                    )
                    if it.kind == "zone":
                        # Greedy host tokenization: the byte after the
                        # entry must end the zone token (zero-fill past
                        # the line end qualifies).
                        nxt = b[:, it.offset + len(entry)]
                        lo = nxt | np.uint8(0x20)
                        zone_char = (
                            ((lo >= np.uint8(ord("a")))
                             & (lo <= np.uint8(ord("z"))))
                            | ((nxt >= np.uint8(ord("0")))
                               & (nxt <= np.uint8(ord("9"))))
                            | (nxt == np.uint8(ord("_")))
                            | (nxt == np.uint8(ord("/")))
                            | (nxt == np.uint8(ord("+")))
                            | (nxt == np.uint8(ord("-")))
                        )
                        m = m & ~zone_char
                    value = jnp.where(m, idx, value)
                    wsel = jnp.where(m, len(entry), wsel)
                    matched = matched | m
                ok = ok & matched
                if it.kind == "zone":
                    # The matched entry maps to its ZoneDeviceTable index;
                    # the offset resolves AFTER the date/time fields are
                    # known (the transition lookup needs the wall clock).
                    zsel = zeros
                    for idx in reversed(range(len(it.zone_idx))):
                        zi = it.zone_idx[idx]
                        if zi:
                            zsel = jnp.where(value == idx, zi, zsel)
                    comp["zone_idx"] = zsel
                elif it.kind == "ampm":
                    comp["ampm"] = value
                elif it.field == "month":
                    month_from_name = value + 1
                # dayofweek is validated but unused (the host resolver
                # ignores it too).
                if seg_w < 0:
                    cursor = cursor + wsel
            else:  # pragma: no cover
                raise AssertionError(it.kind)
        if seg_w >= 0:
            cursor = cursor + seg_w

    # ---- tail zone (parsed at the final cursor) -----------------------
    tail_w = end - cursor
    if dl.tail:
        if one_shot:
            b = shared_win[:, dl.seg_widths[0] :]
        else:
            b = extract(buf, cursor, 6)
        lower = b | np.uint8(0x20)
        tdigits = make_digits(b)

        sign_b = b[:, 0]
        sign = jnp.where(sign_b == np.uint8(ord("-")), -1, 1).astype(jnp.int32)
        sign_ok = (sign_b == np.uint8(ord("+"))) | (sign_b == np.uint8(ord("-")))
        oh, oh_ok = tdigits(1, 2)
        if dl.tail == "offset":
            # ZZ: [+-]HHMM (w==5) or [+-]HH:MM (w==6).
            colon = tail_w == 6
            m_nc, m_nc_ok = tdigits(3, 2)
            m_c, m_c_ok = tdigits(4, 2)
            om = jnp.where(colon, m_c, m_nc)
            om_ok = jnp.where(
                colon, m_c_ok & (b[:, 3] == np.uint8(ord(":"))), m_nc_ok
            )
            ok = ok & ((tail_w == 5) | colon) & sign_ok & oh_ok & om_ok
            comp["offset_seconds"] = sign * (oh * 3600 + om * 60)
        else:
            # XXX: 'Z' (w==1) or [+-]HH:MM (w==6).
            is_z = (tail_w == 1) & (lower[:, 0] == np.uint8(ord("z")))
            om, om_ok = tdigits(4, 2)
            full_ok = (
                (tail_w == 6) & sign_ok & oh_ok & om_ok
                & (b[:, 3] == np.uint8(ord(":")))
            )
            ok = ok & (is_z | full_ok)
            comp["offset_seconds"] = jnp.where(
                is_z, 0, sign * (oh * 3600 + om * 60)
            )
    else:
        ok = ok & (tail_w == 0)
        # Layout default; a zone-text layout overwrites this below once
        # the zone_table block resolves comp["zone_idx"] to an offset.
        comp["offset_seconds"] = jnp.full(
            B, dl.default_offset_seconds, dtype=jnp.int32
        )

    # ---- resolve components (mirrors TimeLayout._resolve) -------------
    year = comp.get("year")
    if year is None:
        year = 2000 + comp["year2"]
    month = comp.get("month", month_from_name)
    day = comp["day"]

    hour = comp.get("hour")
    if hour is None and "clock_hour" in comp:
        ch = comp["clock_hour"]
        # SMART resolver: 0 and 24 both mean midnight; 25+ is invalid.
        ok = ok & (ch <= 24)
        hour = jnp.where(ch == 24, 0, ch)
    if hour is None and "hour12" in comp:
        hour = (comp["hour12"] % 12) + 12 * comp.get("ampm", zeros)
    if hour is None:
        hour = zeros
    minute = comp.get("minute", zeros)
    second = comp.get("second", zeros)
    milli = comp.get("milli", zeros)

    if dl.zone_table is not None and "zone_idx" in comp:
        # Zone-text offset: wall minutes since epoch (days-from-civil,
        # proleptic Gregorian) through the tzdata transition tables.
        # Years outside [1970, 2096] leave the tables' exact window (and
        # would overflow the int32 minute math) — those rows take the
        # oracle, like every other zone-window miss.
        yy = year - (month <= 2)
        era = yy // 400
        yoe = yy - era * 400
        doy = (153 * (month + jnp.where(month > 2, -3, 9)) + 2) // 5 + day - 1
        doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
        days = era * 146097 + doe - 719468
        in_years = (year >= 1970) & (year <= 2096)
        minutes = jnp.where(in_years, days * 1440 + hour * 60 + minute, -1)
        zoff, zok = dl.zone_table.lookup(comp["zone_idx"], minutes)
        comp["offset_seconds"] = zoff
        ok = ok & zok & in_years

    # Range checks = what datetime() construction enforces on the host.
    leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
    thirty = (month == 4) | (month == 6) | (month == 9) | (month == 11)
    dim = jnp.where(thirty, 30,
                    jnp.where(month == 2, jnp.where(leap, 29, 28), 31))
    ok = (
        ok
        & (year >= 1) & (month >= 1) & (month <= 12)
        & (day >= 1) & (day <= dim)
        & (hour <= 23) & (minute <= 59) & (second <= 60) & (milli <= 999)
        # datetime.timezone only admits offsets strictly inside +-24h.
        & (jnp.abs(comp["offset_seconds"]) < 86400)
    )
    second = jnp.minimum(second, 59)  # leap second: SMART clamps 60 -> 59

    return (
        {
            "year": year, "month": month, "day": day, "hour": hour,
            "minute": minute, "second": second, "milli": milli,
            "offset_seconds": comp["offset_seconds"],
        },
        ok,
    )
