"""Device-side generic fixed-layout timestamp parsing.

The host engine compiles every timestamp pattern (java.time subset or
strftime) into a :class:`~logparser_tpu.dissectors.timelayout.TimeLayout` —
a flat item list.  This module compiles the *fixed-width subset* of those
layouts one step further, into a :class:`DeviceTimeLayout` whose every item
sits at a static byte offset, and executes it over ``[B]`` spans of a
``[B, L]`` byte batch as pure vector arithmetic (the TPU replacement for
TimeStampDissector.java:404-424's per-line ``DateTimeFormatter.parse``).

Device-compilable layouts: every item fixed-width (numeric fields with
min==max width, 3-letter month/day names, am/pm, literals), with at most one
variable-width item — the UTC-offset — in tail position (``ZZ`` accepts
``+HHMM``/``+HH:MM`` and ``XXX`` accepts ``Z``/``+HH:MM``; both are
distinguishable by total span width, so a trailing zone stays vectorizable).
This covers the Apache default ``dd/MMM/yyyy:HH:mm:ss ZZ``, nginx
``$time_iso8601`` (``yyyy-MM-dd'T'HH:mm:ssXXX``), and the fixed-width
strftime family (``%d/%b/%Y:%H:%M:%S %z``, ``%Y-%m-%d %H:%M:%S``, ...).
Everything else (variable month names, zone *names* needing tzdata/DST,
week-based dates) stays on the host oracle.

Validation discipline: the device must never accept a span the host layout
rejects (a false-accept would bypass the oracle with a wrong value).  Every
digit is range-checked, literals compare case-insensitively exactly like
``TimeLayout.parse``, month/day names must be table members, and
day-in-month honors leap years.  Device-stricter is fine — a rejected line
falls back to the oracle, which re-applies the exact host semantics.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .postproc import pow10_weights
from ..dissectors.timelayout import (
    DAYS_SHORT,
    MONTHS_SHORT,
    TimeLayout,
)

# Zones that are a fixed UTC offset year-round (no DST), so a layout whose
# default_zone is one of these still compiles to constant offset arithmetic.
_FIXED_OFFSET_ZONES = {"UTC": 0, "GMT": 0, "Z": 0, "UT": 0, "Etc/UTC": 0}


@dataclass(frozen=True)
class _DevItem:
    kind: str        # lit | num | monthname | dayname | ampm
    offset: int      # byte offset from span start
    width: int
    field: str = ""  # for num
    text: bytes = b""  # for lit


@dataclass
class DeviceTimeLayout:
    """A TimeLayout resolved to fixed byte offsets (device-executable)."""

    items: Tuple[_DevItem, ...]
    prefix_width: int              # total width of the fixed items
    tail: str                      # "" | "offset" | "offset_colon"
    default_offset_seconds: int    # applied when tail == ""

    @property
    def max_width(self) -> int:
        return self.prefix_width + (6 if self.tail else 0)


# Numeric layout fields the device models, with their post-parse range
# checks applied in parse_device_timestamp.
_NUM_FIELDS = {
    "year", "year2", "month", "day", "hour", "clock_hour", "hour12",
    "minute", "second", "milli",
}


def compile_layout_for_device(layout: TimeLayout) -> Optional[DeviceTimeLayout]:
    """TimeLayout -> DeviceTimeLayout, or None when any item is outside the
    fixed-width subset (caller keeps the field on the host oracle)."""
    items: List[_DevItem] = []
    offset = 0
    tail = ""
    n = len(layout.items)
    for idx, it in enumerate(layout.items):
        kind = it[0]
        if kind == "lit":
            text = it[1].encode("utf-8", errors="strict")
            items.append(_DevItem("lit", offset, len(text), text=text))
            offset += len(text)
        elif kind == "num":
            _, field, minw, maxw, space_pad = it
            if space_pad or minw != maxw or field not in _NUM_FIELDS:
                return None
            items.append(_DevItem("num", offset, minw, field=field))
            offset += minw
        elif kind == "text":
            _, field, style = it
            if field == "monthname" and style == "short":
                items.append(_DevItem("monthname", offset, 3))
                offset += 3
            elif field == "dayname" and style == "short":
                items.append(_DevItem("dayname", offset, 3))
                offset += 3
            elif field == "ampm":
                items.append(_DevItem("ampm", offset, 2))
                offset += 2
            else:
                return None  # full names are variable-width
        elif kind in ("offset", "offset_colon"):
            if idx != n - 1:
                return None  # variable width is only decodable at the tail
            tail = kind
        else:  # zonetext and anything new: host-only
            return None

    default_offset = 0
    if not tail:
        zone = layout.default_zone
        if zone is not None and zone not in _FIXED_OFFSET_ZONES:
            return None  # DST zones need tzdata; host-only
        default_offset = _FIXED_OFFSET_ZONES.get(zone or "UTC", 0)

    fields = {i.field for i in items if i.kind == "num"}
    has_month = "month" in fields or any(i.kind == "monthname" for i in items)
    if not ((("year" in fields) or ("year2" in fields)) and has_month
            and "day" in fields):
        return None  # incomplete date resolves through host paths
    return DeviceTimeLayout(tuple(items), offset, tail, default_offset)


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------


def _name_hash(name: str) -> int:
    a, b, c = (ord(ch) - 97 for ch in name.lower()[:3])
    return (a * 26 + b) * 26 + c


def parse_device_timestamp(
    buf: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    dl: DeviceTimeLayout,
    extract,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Execute a DeviceTimeLayout over [B] spans.

    Returns (components, ok): components has int32 arrays
    ``year month day hour minute second milli offset_seconds`` (local wall
    clock + UTC offset; epoch math happens host-side in int64).
    """
    B = buf.shape[0]
    b = extract(buf, start, dl.max_width)
    width = end - start
    ok = width >= dl.prefix_width

    zeros = jnp.zeros(B, dtype=jnp.int32)
    comp: Dict[str, jnp.ndarray] = {}

    def digits(off: int, w: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        # One [B, w] vector op chain instead of w scalar-column rounds.
        d = (b[:, off : off + w] - np.uint8(ord("0"))).astype(jnp.int32)
        good = jnp.all((d >= 0) & (d <= 9), axis=1)
        val = jnp.sum(d * pow10_weights(w), axis=1).astype(jnp.int32)
        return val, good

    lower = b | np.uint8(0x20)
    month_from_name = None
    for it in dl.items:
        if it.kind == "lit":
            for i, byte in enumerate(it.text):
                col = it.offset + i
                if ord("a") <= (byte | 0x20) <= ord("z"):
                    ok = ok & (lower[:, col] == np.uint8(byte | 0x20))
                else:
                    ok = ok & (b[:, col] == np.uint8(byte))
        elif it.kind == "num":
            val, good = digits(it.offset, it.width)
            ok = ok & good
            comp[it.field] = val
        elif it.kind == "monthname":
            l0 = (lower[:, it.offset] - np.uint8(ord("a"))).astype(jnp.int32)
            l1 = (lower[:, it.offset + 1] - np.uint8(ord("a"))).astype(jnp.int32)
            l2 = (lower[:, it.offset + 2] - np.uint8(ord("a"))).astype(jnp.int32)
            letters = (
                (l0 >= 0) & (l0 < 26) & (l1 >= 0) & (l1 < 26)
                & (l2 >= 0) & (l2 < 26)
            )
            h = (l0 * 26 + l1) * 26 + l2
            month = zeros
            for m, name in enumerate(MONTHS_SHORT, start=1):
                month = jnp.where(h == _name_hash(name), m, month)
            ok = ok & letters & (month >= 1)
            month_from_name = month
        elif it.kind == "dayname":
            l0 = (lower[:, it.offset] - np.uint8(ord("a"))).astype(jnp.int32)
            l1 = (lower[:, it.offset + 1] - np.uint8(ord("a"))).astype(jnp.int32)
            l2 = (lower[:, it.offset + 2] - np.uint8(ord("a"))).astype(jnp.int32)
            letters = (
                (l0 >= 0) & (l0 < 26) & (l1 >= 0) & (l1 < 26)
                & (l2 >= 0) & (l2 < 26)
            )
            h = (l0 * 26 + l1) * 26 + l2
            known = jnp.zeros(B, dtype=bool)
            for name in DAYS_SHORT:
                known = known | (h == _name_hash(name))
            # The parsed value is validated but unused (the host resolver
            # ignores dayofweek too).
            ok = ok & letters & known
        elif it.kind == "ampm":
            c0 = lower[:, it.offset]
            c1 = lower[:, it.offset + 1]
            is_am = c0 == np.uint8(ord("a"))
            is_pm = c0 == np.uint8(ord("p"))
            ok = ok & (is_am | is_pm) & (c1 == np.uint8(ord("m")))
            comp["ampm"] = jnp.where(is_pm, 1, 0)
        else:  # pragma: no cover
            raise AssertionError(it.kind)

    # ---- tail zone ----------------------------------------------------
    p = dl.prefix_width
    if dl.tail == "offset":
        # ZZ: [+-]HHMM (w==5) or [+-]HH:MM (w==6).
        tail_w = width - p
        colon = tail_w == 6
        sign_b = b[:, p]
        sign = jnp.where(sign_b == np.uint8(ord("-")), -1, 1).astype(jnp.int32)
        sign_ok = (sign_b == np.uint8(ord("+"))) | (sign_b == np.uint8(ord("-")))
        oh, oh_ok = digits(p + 1, 2)
        m_nc, m_nc_ok = digits(p + 3, 2)
        m_c, m_c_ok = digits(p + 4, 2)
        om = jnp.where(colon, m_c, m_nc)
        om_ok = jnp.where(colon, m_c_ok & (b[:, p + 3] == np.uint8(ord(":"))),
                          m_nc_ok)
        ok = ok & ((tail_w == 5) | colon) & sign_ok & oh_ok & om_ok
        comp["offset_seconds"] = sign * (oh * 3600 + om * 60)
    elif dl.tail == "offset_colon":
        # XXX: 'Z' (w==1) or [+-]HH:MM (w==6).
        tail_w = width - p
        is_z = (tail_w == 1) & (lower[:, p] == np.uint8(ord("z")))
        sign_b = b[:, p]
        sign = jnp.where(sign_b == np.uint8(ord("-")), -1, 1).astype(jnp.int32)
        sign_ok = (sign_b == np.uint8(ord("+"))) | (sign_b == np.uint8(ord("-")))
        oh, oh_ok = digits(p + 1, 2)
        om, om_ok = digits(p + 4, 2)
        full_ok = (
            (tail_w == 6) & sign_ok & oh_ok & om_ok
            & (b[:, p + 3] == np.uint8(ord(":")))
        )
        ok = ok & (is_z | full_ok)
        comp["offset_seconds"] = jnp.where(is_z, 0, sign * (oh * 3600 + om * 60))
    else:
        ok = ok & (width == p)
        comp["offset_seconds"] = jnp.full(B, dl.default_offset_seconds,
                                          dtype=jnp.int32)

    # ---- resolve components (mirrors TimeLayout._resolve) -------------
    year = comp.get("year")
    if year is None:
        year = 2000 + comp["year2"]
    month = comp.get("month", month_from_name)
    day = comp["day"]

    hour = comp.get("hour")
    if hour is None and "clock_hour" in comp:
        ch = comp["clock_hour"]
        # SMART resolver: 0 and 24 both mean midnight; 25+ is invalid.
        ok = ok & (ch <= 24)
        hour = jnp.where(ch == 24, 0, ch)
    if hour is None and "hour12" in comp:
        hour = (comp["hour12"] % 12) + 12 * comp.get("ampm", zeros)
    if hour is None:
        hour = zeros
    minute = comp.get("minute", zeros)
    second = comp.get("second", zeros)
    milli = comp.get("milli", zeros)

    # Range checks = what datetime() construction enforces on the host.
    leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
    thirty = (month == 4) | (month == 6) | (month == 9) | (month == 11)
    dim = jnp.where(thirty, 30,
                    jnp.where(month == 2, jnp.where(leap, 29, 28), 31))
    ok = (
        ok
        & (year >= 1) & (month >= 1) & (month <= 12)
        & (day >= 1) & (day <= dim)
        & (hour <= 23) & (minute <= 59) & (second <= 60) & (milli <= 999)
        # datetime.timezone only admits offsets strictly inside +-24h.
        & (jnp.abs(comp["offset_seconds"]) < 86400)
    )
    second = jnp.minimum(second, 59)  # leap second: SMART clamps 60 -> 59

    return (
        {
            "year": year, "month": month, "day": day, "hour": hour,
            "minute": minute, "second": second, "milli": milli,
            "offset_seconds": comp["offset_seconds"],
        },
        ok,
    )
