"""strftime timestamp handling: ``%{strfformat}t`` tokens.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/StrfTimeStampDissector.java
(wraps a TimeStampDissector with a converted layout, :40-68; registers a
LocalizedTimeDissector fallback that re-emits the raw value as
``TIME.LOCALIZEDSTRING``, :104-157) and StrfTimeToDateTimeFormatter.java
(strftime -> formatter mapping; unsupported fields raise; a format without a
zone assumes the default zone, :97-105).  The ANTLR grammar is replaced by a
direct scanner over ``%X`` directives.
"""
from __future__ import annotations

from typing import FrozenSet, List, Optional

from ..core.casts import Cast, STRING_ONLY
from ..core.dissector import Dissector
from ..core.fields import ParsedField
from .timelayout import Item, TimeLayout
from .timestamp import TimeStampDissector

DEFAULT_ZONE = "UTC"


class UnsupportedStrfField(ValueError):
    def __init__(self, field: str):
        super().__init__(
            f"The field '{field}' cannot be converted towards a timestamp layout field."
        )


def compile_strftime(
    strfformat: str, default_zone: str = DEFAULT_ZONE
) -> Optional[TimeLayout]:
    """strftime(3) format -> TimeLayout.  Returns None on syntax errors,
    raises UnsupportedStrfField on unconvertible directives (mirrors
    StrfTimeToDateTimeFormatter.convert)."""
    items: List[Item] = []
    has_zone = False
    i = 0
    n = len(strfformat)
    while i < n:
        # Apache-specific fraction tokens match with or without a leading '%'
        # and beat all other tokenization (StrfTime.g4 lexer order).
        rest = strfformat[i:]
        matched_frac = False
        for frac, field, width in (("msec_frac", "milli", 3), ("usec_frac", "micro", 6)):
            if rest.startswith(frac) or rest.startswith("%" + frac):
                items.append(("num", field, width, width, False))
                i += len(frac) + (1 if rest.startswith("%") else 0)
                matched_frac = True
                break
        if matched_frac:
            continue
        c = strfformat[i]
        if c != "%":
            items.append(("lit", c))
            i += 1
            continue
        if i + 1 >= n:
            return None  # dangling % = syntax error
        d = strfformat[i + 1]
        i += 2
        if d in ("E", "O") and i < n:
            # E/O alternative-format modifiers are ignored (StrfTime.g4:40).
            d = strfformat[i]
            i += 1
        if d == "%":
            items.append(("lit", "%"))
        elif d == "n":
            items.append(("lit", "\n"))
        elif d == "t":
            items.append(("lit", "\t"))
        elif d == "a":
            items.append(("text", "dayname", "short"))
        elif d == "A":
            items.append(("text", "dayname", "full"))
        elif d in ("b", "h"):
            items.append(("text", "monthname", "short"))
        elif d == "B":
            items.append(("text", "monthname", "full"))
        elif d == "d":
            items.append(("num", "day", 2, 2, False))
        elif d == "D":  # %m/%d/%y
            items.append(("num", "month", 2, 2, False))
            items.append(("lit", "/"))
            items.append(("num", "day", 2, 2, False))
            items.append(("lit", "/"))
            items.append(("num", "year2", 2, 2, False))
        elif d == "e":
            items.append(("num", "day", 1, 2, True))
        elif d == "F":  # %Y-%m-%d
            items.append(("num", "year", 4, 4, False))
            items.append(("lit", "-"))
            items.append(("num", "month", 2, 2, False))
            items.append(("lit", "-"))
            items.append(("num", "day", 2, 2, False))
        elif d == "G":
            items.append(("num", "wby", 4, 4, False))
        elif d == "g":
            items.append(("num", "wby2", 2, 2, False))
        elif d == "H":
            # Reference maps %H to CLOCK_HOUR_OF_DAY (1-24); see
            # StrfTimeToDateTimeFormatter enterPH.
            items.append(("num", "clock_hour", 2, 2, False))
        elif d == "I":
            items.append(("num", "hour12", 2, 2, False))
        elif d == "j":
            items.append(("num", "doy", 3, 3, False))
        elif d == "k":
            items.append(("num", "hour", 1, 2, True))
        elif d == "l":
            items.append(("num", "hour12", 1, 2, True))
        elif d == "m":
            items.append(("num", "month", 2, 2, False))
        elif d == "M":
            items.append(("num", "minute", 2, 2, False))
        elif d == "p":
            items.append(("text", "ampm", "upper"))
        elif d == "P":
            items.append(("text", "ampm", "lower"))
        elif d == "r":  # %I:%M:%S %p
            items.append(("num", "hour12", 2, 2, False))
            items.append(("lit", ":"))
            items.append(("num", "minute", 2, 2, False))
            items.append(("lit", ":"))
            items.append(("num", "second", 2, 2, False))
            items.append(("lit", " "))
            items.append(("text", "ampm", "upper"))
        elif d == "R":  # %H:%M
            items.append(("num", "hour", 2, 2, False))
            items.append(("lit", ":"))
            items.append(("num", "minute", 2, 2, False))
        elif d == "s":
            items.append(("num", "epoch", 1, 19, False))
        elif d == "S":
            items.append(("num", "second", 2, 2, False))
        elif d == "T":  # %H:%M:%S
            items.append(("num", "hour", 2, 2, False))
            items.append(("lit", ":"))
            items.append(("num", "minute", 2, 2, False))
            items.append(("lit", ":"))
            items.append(("num", "second", 2, 2, False))
        elif d == "u":
            items.append(("num", "isodow", 1, 1, False))
        elif d == "V":
            items.append(("num", "isoweek", 1, 2, False))
        elif d == "W":
            items.append(("num", "isoweek", 2, 2, False))
        elif d == "y":
            items.append(("num", "year2", 2, 2, False))
        elif d == "Y":
            items.append(("num", "year", 4, 4, False))
        elif d == "z":
            items.append(("offset",))
            has_zone = True
        elif d == "Z":
            items.append(("zonetext",))
            has_zone = True
        elif d in ("c", "C", "U", "w", "x", "X", "+"):
            raise UnsupportedStrfField("%" + d)
        else:
            return None  # unknown directive = lexer/syntax error

    merged: List[Item] = []
    for it in items:
        if it[0] == "lit" and merged and merged[-1][0] == "lit":
            merged[-1] = ("lit", merged[-1][1] + it[1])
        else:
            merged.append(it)
    return TimeLayout(merged, None if has_zone else default_zone)


class StrfTimeStampDissector(Dissector):
    """Handles ``%{strfformat}t``: converts the strftime pattern to a layout
    and delegates to an embedded TimeStampDissector."""

    def __init__(self):
        self.timestamp_dissector = TimeStampDissector()
        self.strf_pattern: Optional[str] = None
        self._input_type = "TIME.?????"
        # One LocalizedTimeDissector per instance: create_additional runs
        # again on every re-assembly (e.g. after set_locale), and
        # add_dissector dedups by identity — a fresh instance per call
        # would accumulate duplicates.
        self._localized: Optional["LocalizedTimeDissector"] = None

    def set_date_time_pattern(self, pattern: Optional[str]) -> None:
        if pattern is None:
            self.timestamp_dissector.set_date_time_pattern("")
            return
        if pattern == self.strf_pattern:
            return
        self.strf_pattern = pattern
        layout = compile_strftime(pattern)
        if layout is None:
            raise UnsupportedStrfField(pattern)
        self.timestamp_dissector.set_layout(layout)

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.set_date_time_pattern(settings)
        return True

    def set_locale(self, locale) -> "StrfTimeStampDissector":
        """Delegates to the embedded TimeStampDissector (the reference's
        wrapped-dissector shape keeps one locale, TimeStampDissector.java
        :73-78)."""
        self.timestamp_dissector.set_locale(locale)
        return self

    def dissect(self, parsable, input_name: str) -> None:
        field: ParsedField = parsable.get_parsable_field(self._input_type, input_name)
        self.timestamp_dissector.dissect_field(parsable, input_name, field)

    def get_input_type(self) -> str:
        return self._input_type

    def set_input_type(self, new_input_type: str) -> None:
        self._input_type = new_input_type

    def get_possible_output(self) -> List[str]:
        return self.timestamp_dissector.get_possible_output()

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        return self.timestamp_dissector.prepare_for_dissect(input_name, output_name)

    def prepare_for_run(self) -> None:
        self.timestamp_dissector.prepare_for_run()

    def get_new_instance(self) -> "Dissector":
        new = StrfTimeStampDissector()
        self.initialize_new_instance(new)
        return new

    def initialize_new_instance(self, new_instance: "Dissector") -> None:
        new_instance.set_input_type(self._input_type)
        new_instance.set_locale(self.timestamp_dissector.locale)
        if self.strf_pattern is not None:
            new_instance.set_date_time_pattern(self.strf_pattern)

    def create_additional_dissectors(self, parser) -> None:
        if self._localized is None:
            self._localized = LocalizedTimeDissector(self._input_type)
        self._localized.set_input_type(self._input_type)
        parser.add_dissector(self._localized)


class LocalizedTimeDissector(Dissector):
    """Fallback that re-emits the raw strftime timestamp value as
    ``TIME.LOCALIZEDSTRING`` (StrfTimeStampDissector.java:104-157)."""

    def __init__(self, input_type: Optional[str] = None):
        self._input_type = input_type

    def set_input_type(self, new_input_type: str) -> None:
        self._input_type = new_input_type

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.set_input_type(settings)
        return True

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self._input_type, input_name)
        parsable.add_dissection(input_name, "TIME.LOCALIZEDSTRING", "", field.value)

    def get_input_type(self) -> str:
        return self._input_type

    def get_possible_output(self) -> List[str]:
        return ["TIME.LOCALIZEDSTRING:"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        return STRING_ONLY

    def get_new_instance(self) -> "Dissector":
        return LocalizedTimeDissector(self._input_type)
