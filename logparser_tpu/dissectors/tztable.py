"""tzdata -> device transition tables for %Z zone TEXT.

The reference parses zone names inline through java.time's tzdata
(TimeStampDissector.java:404-424).  The rebuild's host oracle resolves
them through ``zoneinfo`` (timelayout._parse_zonetext); this module makes
the same resolution DEVICE-resident: each supported zone's TZif file is
read directly (RFC 8536; own reader, like the repo's own MaxMind-DB
reader) and compiled into a wall-clock transition table under the
oracle's ``fold=0`` semantics, so a batch of timestamps looks its UTC
offsets up with one ``jnp.searchsorted`` — the same O(log K) SIMD join
as the GeoIP range tables (geoip/device.py).

fold=0 wall-clock boundary rule (PEP 495, locked by differential tests
against zoneinfo in tests/test_tztable.py): around a UTC transition at
``t`` from offset ``o_prev`` to ``o_new``, ``utcoffset`` of a naive
local time with fold=0 switches exactly at local ``t + max(o_prev,
o_new)`` — ambiguous times (backward jump) take the PRE-transition
offset, gap times (forward jump) extrapolate with it.

Bounds (the ADR): local wall minutes span [epoch, epoch + 2^26 min ≈
year 2097]; zones whose TZif footer carries an active DST rule are valid
on device only up to their last explicit transition (tzdata precomputes
those through ~2037) — later rows, pre-1970 rows, and zones outside the
device vocabulary fall back to the host oracle, which resolves them
through zoneinfo identically.  The vocabulary is capped at 63 zones so
(zone_idx, minute) packs into one uint32 searchsorted key.
"""
from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Minutes per zone segment of the packed uint32 key space: covers
# 1970..2097; zone index must stay < 64.
SPAN_MINUTES = 1 << 26

# Bias added to offset seconds inside the packed [T, 2] uint32 device
# table (columns: key, offset + bias): UTC offsets span [-12h, +14h] in
# seconds, so +2^17 keeps them representable as uint32.
_OFFSET_BIAS = 1 << 17

# Canonical zones the curated abbreviation table maps into
# (timelayout._ZONE_ABBREVIATIONS values).
_ABBREVIATION_TARGETS = [
    "UTC", "CET", "MET", "WET", "EET",
    "EST5EDT", "CST6CDT", "MST7MDT", "PST8PDT",
]

# Default region-id vocabulary: the canonical targets plus widespread
# region ids.  Total must stay under 64 (uint32 key packing).
DEFAULT_DEVICE_ZONES = _ABBREVIATION_TARGETS + [
    "Etc/UTC", "GMT",
    "America/New_York", "America/Chicago", "America/Denver",
    "America/Los_Angeles", "America/Phoenix", "America/Anchorage",
    "America/Toronto", "America/Mexico_City", "America/Sao_Paulo",
    "America/Argentina/Buenos_Aires",
    "Europe/London", "Europe/Dublin", "Europe/Lisbon", "Europe/Paris",
    "Europe/Berlin", "Europe/Madrid", "Europe/Rome", "Europe/Amsterdam",
    "Europe/Brussels", "Europe/Zurich", "Europe/Vienna", "Europe/Prague",
    "Europe/Warsaw", "Europe/Stockholm", "Europe/Oslo",
    "Europe/Helsinki", "Europe/Athens",
    "Europe/Bucharest", "Europe/Istanbul", "Europe/Moscow", "Europe/Kyiv",
    "Asia/Tokyo", "Asia/Shanghai", "Asia/Hong_Kong", "Asia/Singapore",
    "Asia/Seoul", "Asia/Taipei", "Asia/Kolkata", "Asia/Karachi",
    "Asia/Dubai", "Asia/Jerusalem", "Asia/Bangkok", "Asia/Jakarta",
    "Asia/Manila",
    "Australia/Sydney", "Australia/Melbourne", "Australia/Perth",
    "Pacific/Auckland",
    "Africa/Cairo", "Africa/Johannesburg", "Africa/Lagos",
    "Africa/Nairobi",
]
assert len(DEFAULT_DEVICE_ZONES) < 64, "uint32 key packing caps zones at 63"


def _tzpath_candidates() -> List[str]:
    try:
        import zoneinfo

        paths = list(zoneinfo.TZPATH)
    except Exception:  # pragma: no cover - zoneinfo is stdlib
        paths = []
    return paths or ["/usr/share/zoneinfo"]


def read_tzif(zone: str) -> Optional[Tuple[List[int], List[int], int, bool]]:
    """Read a TZif file (RFC 8536): (utc transition times, offset after
    each transition, offset before the first transition, footer has an
    active DST rule).  None when the zone file is missing/unreadable."""
    path = None
    for base in _tzpath_candidates():
        cand = os.path.join(base, *zone.split("/"))
        if os.path.isfile(cand):
            path = cand
            break
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None

    def parse_block(buf: bytes, pos: int, time_size: int):
        if buf[pos:pos + 4] != b"TZif":
            raise ValueError("bad magic")
        version = buf[pos + 4:pos + 5]
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt) = (
            struct.unpack(">6I", buf[pos + 20:pos + 44])
        )
        p = pos + 44
        fmt = ">%d%s" % (timecnt, "q" if time_size == 8 else "l")
        times = list(struct.unpack(fmt, buf[p:p + timecnt * time_size]))
        p += timecnt * time_size
        type_idx = list(buf[p:p + timecnt])
        p += timecnt
        ttinfo = []
        for _ in range(typecnt):
            utoff, _isdst, _desig = struct.unpack(">lBB", buf[p:p + 6])
            ttinfo.append(utoff)
            p += 6
        p += charcnt
        p += leapcnt * (time_size + 4)
        p += isstdcnt + isutcnt
        return version, times, type_idx, ttinfo, p

    try:
        version, times, type_idx, ttinfo, end = parse_block(data, 0, 4)
        footer = b""
        if version >= b"2":
            # 64-bit section follows the v1 block, then the TZ footer.
            _, times, type_idx, ttinfo, end = parse_block(data, end, 8)
            footer = data[end:]
        if not ttinfo:
            return None
        offsets = [ttinfo[i] for i in type_idx]
        # Offset before the first transition: type 0.  (RFC 8536 says the
        # first *standard-time* type; type 0 is the near-universal file
        # convention, and _validate_against_zoneinfo drops any zone where
        # the two disagree, so the simpler rule is safe here.)
        base = ttinfo[0]
        # Footer like "\nCET-1CEST,M3.5.0,M10.5.0/3\n": a comma means an
        # active DST rule governs times past the last transition.
        footer_dst = b"," in footer
        return times, offsets, base, footer_dst
    except (ValueError, struct.error, IndexError):
        return None


def wall_table(
    zone: str, span_minutes: int = SPAN_MINUTES
) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """Wall-clock (fold=0) transition table for one zone:
    (boundaries_min int32 ascending — first entry 0, offsets_s int32 per
    segment, valid_until_min).  None when the zone cannot be represented
    exactly (missing file, non-minute-aligned boundary, non-monotone
    wall boundaries)."""
    got = read_tzif(zone)
    if got is None:
        return None
    times, offsets, base, footer_dst = got

    bounds: List[int] = [0]
    segs: List[int] = []
    cur = base
    # Offset in effect at wall minute 0 = offset at the last transition
    # with wall boundary <= 0.
    wall_bounds: List[Tuple[int, int]] = []  # (wall_seconds, offset_after)
    prev = base
    for t, off in zip(times, offsets):
        if off == prev:
            # No-op transition (e.g. the INT32_MAX sentinel some tzdata
            # builds append): no wall-clock boundary.
            continue
        wall = t + max(prev, off)
        wall_bounds.append((wall, off))
        prev = off
    base_off = base
    for wall, off in wall_bounds:
        if wall <= 0:
            base_off = off
    segs = [base_off]
    last_bound = 0
    for wall, off in wall_bounds:
        if wall <= 0:
            continue
        if wall % 60 != 0:
            return None  # sub-minute boundary: keep the zone on the host
        m = wall // 60
        if m >= span_minutes:
            break
        if m <= last_bound:
            return None  # non-monotone wall clock: host-only
        bounds.append(m)
        segs.append(off)
        last_bound = m
    valid_until = span_minutes - 1
    if footer_dst:
        # Past the last explicit transition the footer's DST rule takes
        # over; the device table is only exact up to that point.
        valid_until = last_bound if last_bound > 0 else 0
    return (
        np.asarray(bounds, dtype=np.int64),
        np.asarray(segs, dtype=np.int32),
        valid_until,
    )


def _probe_offset(zone_obj, minute: int) -> Optional[int]:
    """zoneinfo ground truth: utcoffset (fold=0) at a wall minute."""
    import datetime as _dt

    days, rem = divmod(minute, 1440)
    try:
        local = _dt.datetime(1970, 1, 1) + _dt.timedelta(
            days=days, minutes=rem
        )
        delta = local.replace(tzinfo=zone_obj, fold=0).utcoffset()
        return int(delta.total_seconds())
    except (OverflowError, ValueError):
        return None


def _validate_against_zoneinfo(
    zone: str, bounds: np.ndarray, segs: np.ndarray, valid_until: int
) -> bool:
    """Build-time self-check: every derived segment's offset must equal
    zoneinfo's fold=0 utcoffset just at and just before each boundary —
    so the device table can NEVER silently disagree with the oracle's
    tzdata path (TimeLayout._parse_zonetext resolves through zoneinfo)."""
    try:
        from zoneinfo import ZoneInfo

        zobj = ZoneInfo(zone)
    except Exception:
        return False
    bl = bounds.tolist()
    sl = segs.tolist()
    for i, (b, off) in enumerate(zip(bl, sl)):
        probe_at = b if b < valid_until else None
        if probe_at is not None and _probe_offset(zobj, probe_at) != off:
            return False
        if i > 0:
            before = bl[i] - 1
            if before < valid_until and _probe_offset(
                zobj, before
            ) != sl[i - 1]:
                return False
    if valid_until > 0:
        last = min(valid_until - 1, bl[-1] + 2 * 365 * 1440)
        if last >= bl[-1] and _probe_offset(zobj, last) != sl[-1]:
            return False
    return True


@dataclass
class ZoneDeviceTable:
    """Device arrays for a zone vocabulary: packed uint32 keys
    (zone_idx * SPAN + wall_minute) + per-segment offsets, resolved on
    device via a bucketed direct index.

    ``jnp.searchsorted`` over the packed table lowers to an XLA while
    loop of ~log2(T) dependent [B] fusions — profiled at 1.5 ms/batch
    @16k, 75% of the whole %Z kernel.  Instead, a host-precomputed
    bucket table maps ``key >> BUCKET_BITS`` (2^14 minutes ≈ 11.4 days
    per bucket) to the last transition index at or before the bucket
    start; tz transitions are months apart, so at most ``chain`` (~1-2,
    asserted at build time) unrolled gather+compare steps finish the
    resolution — a handful of parallel fusions instead of a serial
    binary-search loop."""

    BUCKET_BITS = 14

    zones: Tuple[str, ...]
    keys: np.ndarray          # [T] uint32 ascending
    offsets_s: np.ndarray     # [T] int32
    valid_until: np.ndarray   # [Z] int32 (exclusive wall-minute bound)
    buckets: np.ndarray       # [Z << (26 - BUCKET_BITS)] int32
    chain: int                # max in-bucket transition steps

    @classmethod
    def build(cls, zones: Sequence[str]) -> "ZoneDeviceTable":
        if len(zones) >= 64:
            raise ValueError("device zone vocabulary caps at 63 zones")
        kept: List[str] = []
        keys: List[int] = []
        offs: List[int] = []
        valid: List[int] = []
        for zone in zones:
            table = wall_table(zone)
            if table is None:
                continue
            bounds, segs, valid_until = table
            if not _validate_against_zoneinfo(zone, bounds, segs,
                                              valid_until):
                continue  # any disagreement: the zone stays host-only
            z = len(kept)
            kept.append(zone)
            for b, o in zip(bounds.tolist(), segs.tolist()):
                keys.append(z * SPAN_MINUTES + b)
                offs.append(o)
            valid.append(valid_until)
        keys_a = np.asarray(keys, dtype=np.uint32)
        n_buckets = len(kept) << (26 - cls.BUCKET_BITS)
        starts = np.arange(n_buckets, dtype=np.uint64) << cls.BUCKET_BITS
        # Last key <= bucket start (side='right' - 1, clipped like the
        # query path).
        buckets = np.maximum(
            np.searchsorted(keys_a, starts, side="right") - 1, 0
        ).astype(np.int32)
        # Max keys strictly inside any bucket = the unrolled step count a
        # query may need past its bucket's base index.
        if len(keys_a):
            ends = starts + np.uint64((1 << cls.BUCKET_BITS) - 1)
            per_bucket = (
                np.searchsorted(keys_a, ends, side="right")
                - np.searchsorted(keys_a, starts, side="right")
            )
            chain = int(per_bucket.max()) if n_buckets else 0
        else:
            chain = 0
        # The unrolled device loop must stay a handful of fusions — real
        # tz transitions are months apart (chain is 1 for the shipped
        # 63-zone vocabulary).  A dense-transition zone would silently
        # re-grow toward the serial searchsorted cost this scheme
        # replaced; fail LOUDLY at build time instead.
        if chain > 4:
            raise ValueError(
                f"zone vocabulary needs {chain} in-bucket steps (>4); "
                "shrink BUCKET_BITS or drop the dense-transition zone"
            )
        return cls(
            tuple(kept),
            keys_a,
            np.asarray(offs, dtype=np.int32),
            np.asarray(valid, dtype=np.int32),
            buckets,
            chain,
        )

    def lookup(self, zone_idx, minutes):
        """[B] zone indices + [B] wall minutes -> (offset_s, ok).
        Jittable; rows with ok=False (outside a zone's exact window)
        must route to the oracle."""
        import jax.numpy as jnp

        m = jnp.clip(minutes, 0, SPAN_MINUTES - 1).astype(jnp.uint32)
        key = zone_idx.astype(jnp.uint32) * np.uint32(SPAN_MINUTES) + m
        T = len(self.keys)
        idx = jnp.asarray(self.buckets)[
            (key >> np.uint32(self.BUCKET_BITS)).astype(jnp.int32)
        ]
        # keys and offsets ride ONE [T, 2] uint32 table (key, offset +
        # bias): each [B] gather is its own ~0.12 ms fusion at 16k, so
        # the chain compare and the final offset resolve from a single
        # row gather per step instead of two separate tables.  (Not an
        # int64 pack: default-x64-disabled JAX would silently downcast
        # it.)
        packed = jnp.asarray(self._packed_keys_offsets())
        last = max(T - 1, 0)
        cur = packed[idx]
        for _ in range(self.chain):
            nxt = jnp.minimum(idx + 1, last)
            cand = packed[nxt]
            adv = cand[:, 0] <= key
            cur = jnp.where(adv[:, None], cand, cur)
            idx = jnp.where(adv, nxt, idx)
        off = cur[:, 1].astype(jnp.int32) - np.int32(_OFFSET_BIAS)
        ok = (
            (minutes >= 0)
            & (minutes < jnp.asarray(self.valid_until)[zone_idx])
        )
        return off, ok

    def _packed_keys_offsets(self) -> np.ndarray:
        """[T, 2] uint32 rows of (key, offset_s + _OFFSET_BIAS).  The
        bias keeps negative UTC offsets representable in uint32 without
        touching the key compare in column 0; cached per table."""
        got = getattr(self, "_packed_cache", None)
        if got is None:
            got = np.stack(
                [
                    self.keys.astype(np.uint32),
                    (self.offsets_s.astype(np.int64)
                     + _OFFSET_BIAS).astype(np.uint32),
                ],
                axis=1,
            )
            self._packed_cache = got
        return got


_TABLE_CACHE: Dict[Tuple[str, ...], ZoneDeviceTable] = {}


def default_zone_table() -> ZoneDeviceTable:
    key = tuple(DEFAULT_DEVICE_ZONES)
    got = _TABLE_CACHE.get(key)
    if got is None:
        got = _TABLE_CACHE[key] = ZoneDeviceTable.build(key)
    return got
