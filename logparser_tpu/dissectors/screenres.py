"""Screen resolution dissection: "1024x768" -> width/height.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/ScreenResolutionDissector.java
(:59-76; separator configurable via the settings parameter).
"""
from __future__ import annotations

from typing import FrozenSet, List, Set

from ..core.casts import Cast, NO_CASTS, STRING_OR_LONG
from ..core.dissector import Dissector, extract_field_name

SCREENRESOLUTION = "SCREENRESOLUTION"


class ScreenResolutionDissector(Dissector):
    def __init__(self, separator: str = "x"):
        self.separator = separator
        self.wanted: Set[str] = set()

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        if settings:
            self.separator = settings
        return True

    def get_input_type(self) -> str:
        return SCREENRESOLUTION

    def get_possible_output(self) -> List[str]:
        return ["SCREENWIDTH:width", "SCREENHEIGHT:height"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        if name in ("width", "height"):
            self.wanted.add(name)
            return STRING_OR_LONG
        return NO_CASTS

    def get_new_instance(self) -> "Dissector":
        return ScreenResolutionDissector(self.separator)

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(SCREENRESOLUTION, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return
        if self.separator in value:
            parts = value.split(self.separator)
            if "width" in self.wanted:
                parsable.add_dissection(input_name, "SCREENWIDTH", "width", parts[0])
            if "height" in self.wanted:
                parsable.add_dissection(input_name, "SCREENHEIGHT", "height", parts[1])
