"""Byte-level decoding utilities with Java-exact semantics.

Rebuild of httpdlog/httpdlog-parser/.../httpdlog/Utils.java:

- :func:`resilient_url_decode` (Utils.java:38-65): tolerant URL decoding that
  survives chopped %-escapes and the rejected ``%uXXXX`` encoding, via the
  UTF-16 re-encode trick: every ``%hh`` becomes ``%00%hh`` and ``%uABCD``
  becomes ``%AB%CD``, then the whole string is URL-decoded as UTF-16.
  Malformed interior escapes raise ValueError (Java: IllegalArgumentException
  from URLDecoder), which callers catch per-field.
- :func:`decode_apache_httpd_log_value` (Utils.java:147-201): the inverse of
  Apache HTTPD's ap_escape_logitem — ``\\"``, ``\\\\``, C-style whitespace
  escapes, and ``\\xhh``.  Replicates the Java ``(char)(byte)`` sign-extension
  quirk: bytes >= 0x80 become U+FF80..U+FFFF, not U+0080..U+00FF.
"""
from __future__ import annotations

import re
from typing import Optional

_VALID_STANDARD = re.compile("%([0-9A-Fa-f]{2})")
_CHOPPED_STANDARD = re.compile("%[0-9A-Fa-f]?$")
_VALID_NON_STANDARD = re.compile("%u([0-9A-Fa-f][0-9A-Fa-f])([0-9A-Fa-f][0-9A-Fa-f])")
_CHOPPED_NON_STANDARD = re.compile("%u[0-9A-Fa-f]{0,3}$")

_HEX = "0123456789abcdef"


def hex_chars_to_byte(c1: str, c2: str) -> int:
    """Two hex characters -> byte value 0..255; ValueError on non-hex."""
    hi = _HEX.find(c1.lower())
    lo = _HEX.find(c2.lower())
    if hi < 0:
        raise ValueError(f"URLDecoder: Illegal hex characters (char 1): '{c1}'")
    if lo < 0:
        raise ValueError(f"URLDecoder: Illegal hex characters (char 2): '{c2}'")
    return (hi << 4) | lo


def _decode_utf16_bytes(b: bytes) -> str:
    """Java ``new String(bytes, "UTF-16")``: BOM-sniffing, big-endian default,
    malformed input replaced with U+FFFD."""
    if b.startswith(b"\xfe\xff"):
        return b[2:].decode("utf-16-be", errors="replace")
    if b.startswith(b"\xff\xfe"):
        return b[2:].decode("utf-16-le", errors="replace")
    return b.decode("utf-16-be", errors="replace")


def _url_decode_utf16(s: str) -> str:
    """java.net.URLDecoder.decode(s, "UTF-16"): '+' -> ' '; each maximal run of
    ``%XX`` escapes is collected into bytes and decoded as one UTF-16 string;
    malformed/incomplete escapes raise ValueError."""
    out = []
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if c == "+":
            out.append(" ")
            i += 1
        elif c == "%":
            run = bytearray()
            while i < n and s[i] == "%":
                hex2 = s[i + 1 : i + 3]
                if len(hex2) != 2:
                    raise ValueError(
                        "URLDecoder: Incomplete trailing escape (%) pattern"
                    )
                try:
                    run.append(int(hex2, 16))
                except ValueError:
                    raise ValueError(
                        f'URLDecoder: Illegal hex characters in escape (%) pattern : "{hex2}"'
                    ) from None
                i += 3
            out.append(_decode_utf16_bytes(bytes(run)))
        else:
            out.append(c)
            i += 1
    return "".join(out)


def resilient_url_decode(input_str: str) -> str:
    cooked = input_str
    if "%" in cooked:
        # Transform all existing UTF-8 standard escapes into UTF-16 escapes.
        cooked = _VALID_STANDARD.sub("%00%\\1", cooked)
        # Discard a chopped encoded char at the end of the line.
        cooked = _CHOPPED_STANDARD.sub("", cooked)
        if "%u" in cooked:
            cooked = _VALID_NON_STANDARD.sub("%\\1%\\2", cooked)
            cooked = _CHOPPED_NON_STANDARD.sub("", cooked)
    return _url_decode_utf16(cooked)


def decode_apache_httpd_log_value(input_str: Optional[str]) -> Optional[str]:
    if input_str is None or input_str == "":
        return input_str
    if "\\" not in input_str:
        return input_str

    out = []
    i = 0
    n = len(input_str)
    while i < n:
        chr_ = input_str[i]
        if chr_ == "\\":
            i += 1
            chr_ = input_str[i]  # IndexError mirrors Java's StringIndexOutOfBounds
            if chr_ in ('"', "\\"):
                out.append(chr_)
            elif chr_ == "b":
                out.append("\b")
            elif chr_ == "n":
                out.append("\n")
            elif chr_ == "r":
                out.append("\r")
            elif chr_ == "t":
                out.append("\t")
            elif chr_ == "v":
                out.append("\x0b")
            elif chr_ == "x":
                b = hex_chars_to_byte(input_str[i + 1], input_str[i + 2])
                i += 2
                # Java appends (char)(byte)b — sign-extension maps >=0x80
                # to U+FF80..U+FFFF.
                out.append(chr(b if b < 0x80 else 0xFF00 | b))
            else:
                # Shouldn't happen; append unmodified.
                out.append("\\")
                out.append(chr_)
        else:
            out.append(chr_)
        i += 1
    return "".join(out)
