"""Sub-dissector library: tokenformat compiler, time, URI, query, cookies, etc."""
from .cookies import (
    RequestCookieListDissector,
    ResponseSetCookieDissector,
    ResponseSetCookieListDissector,
)
from .firstline import HttpFirstLineDissector, HttpFirstLineProtocolDissector
from .mod_unique_id import ModUniqueIdDissector
from .query import QueryStringFieldDissector
from .screenres import ScreenResolutionDissector
from .strftime_stamp import LocalizedTimeDissector, StrfTimeStampDissector
from .timestamp import TimeStampDissector
from .translate import (
    ConvertCLFIntoNumber,
    ConvertMillisecondsIntoMicroseconds,
    ConvertNumberIntoCLF,
    ConvertSecondsWithMillisStringDissector,
)
from .uri import HttpUriDissector

__all__ = [
    "RequestCookieListDissector",
    "ResponseSetCookieDissector",
    "ResponseSetCookieListDissector",
    "HttpFirstLineDissector",
    "HttpFirstLineProtocolDissector",
    "ModUniqueIdDissector",
    "QueryStringFieldDissector",
    "ScreenResolutionDissector",
    "StrfTimeStampDissector",
    "LocalizedTimeDissector",
    "TimeStampDissector",
    "ConvertCLFIntoNumber",
    "ConvertMillisecondsIntoMicroseconds",
    "ConvertNumberIntoCLF",
    "ConvertSecondsWithMillisStringDissector",
    "HttpUriDissector",
]
