"""HTTP request first-line dissection ("GET /x HTTP/1.1" -> method/uri/protocol).

Rebuild of httpdlog/httpdlog-parser/.../dissectors/HttpFirstLineDissector.java
(split regex :59-60 with truncated-line fallback :62-63, 108-121) and
HttpFirstLineProtocolDissector.java (protocol/version split on ``/`` :54-77).
"""
from __future__ import annotations

import re
from typing import FrozenSet, List, Set

from ..core.casts import Cast, STRING_ONLY
from ..core.dissector import Dissector, extract_field_name


class HttpFirstLineDissector(Dissector):
    # The token regex is just '.*' so garbage survives the skeleton match;
    # the real structure check happens here.
    FIRSTLINE_REGEX = ".*"

    _SPLITTER = re.compile(r"^([a-zA-Z-_]+) (.*) (HTTP/[0-9]+\.[0-9]+)$")
    _TOO_LONG_SPLITTER = re.compile(r"^([a-zA-Z-_]+) (.*)$")

    INPUT_TYPE = "HTTP.FIRSTLINE"

    def __init__(self):
        self.requested: Set[str] = set()

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return [
            "HTTP.METHOD:method",
            "HTTP.URI:uri",
            "HTTP.PROTOCOL_VERSION:protocol",
        ]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested.add(extract_field_name(input_name, output_name))
        return STRING_ONLY

    def get_new_instance(self) -> "Dissector":
        return HttpFirstLineDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "" or value == "-":
            return

        m = self._SPLITTER.search(value)
        if m is not None:
            self._output(parsable, input_name, "HTTP.METHOD", "method", m.group(1))
            self._output(parsable, input_name, "HTTP.URI", "uri", m.group(2))
            self._output(
                parsable, input_name, "HTTP.PROTOCOL_VERSION", "protocol", m.group(3)
            )
            return

        # The request URI may have been so long that the protocol was cut off.
        m = self._TOO_LONG_SPLITTER.search(value)
        if m is not None:
            self._output(parsable, input_name, "HTTP.METHOD", "method", m.group(1))
            self._output(parsable, input_name, "HTTP.URI", "uri", m.group(2))
            parsable.add_dissection(
                input_name, "HTTP.PROTOCOL_VERSION", "protocol", None
            )

    def _output(self, parsable, input_name, ftype, name, value) -> None:
        if name in self.requested:
            parsable.add_dissection(input_name, ftype, name, value)


class HttpFirstLineProtocolDissector(Dissector):
    """HTTP.PROTOCOL_VERSION ("HTTP/1.1") -> protocol + version."""

    INPUT_TYPE = "HTTP.PROTOCOL_VERSION"

    def __init__(self):
        self.requested: Set[str] = set()

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return ["HTTP.PROTOCOL:", "HTTP.PROTOCOL.VERSION:version"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested.add(extract_field_name(input_name, output_name))
        return STRING_ONLY

    def get_new_instance(self) -> "Dissector":
        return HttpFirstLineProtocolDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "" or value == "-":
            return

        parts = value.split("/", 1)
        if len(parts) == 2:
            self._output(parsable, input_name, "HTTP.PROTOCOL", "", parts[0])
            self._output(
                parsable, input_name, "HTTP.PROTOCOL.VERSION", "version", parts[1]
            )
            return

        # Truncated first line: emit explicit nulls.
        parsable.add_dissection(input_name, "HTTP.PROTOCOL", "", None)
        parsable.add_dissection(input_name, "HTTP.PROTOCOL.VERSION", "version", None)

    def _output(self, parsable, input_name, ftype, name, value) -> None:
        if name in self.requested:
            parsable.add_dissection(input_name, ftype, name, value)
