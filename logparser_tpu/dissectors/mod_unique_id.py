"""mod_unique_id token decoding: 24 chars -> epoch/ip/processid/counter/threadindex.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/ModUniqueIdDissector.java:
the encoding is base64 with a different alphabet tail; the reference maps the
``+``/``/`` characters to ``@`` and reuses a standard base64 decoder
(:117-150).  Layout of the 18 decoded bytes: 32-bit timestamp (seconds),
32-bit IPv4, 32-bit pid, 16-bit counter, 32-bit thread index.
"""
from __future__ import annotations

import base64
from typing import FrozenSet, List, Optional, Set

from ..core.casts import Cast, NO_CASTS, STRING_OR_LONG
from ..core.dissector import Dissector, extract_field_name


def _decode_to_bytes(unique_id: str) -> Optional[bytes]:
    if len(unique_id) != 24:
        return None
    # The mod_unique_id alphabet is [A-Za-z0-9@-]; '@' and '-' replace base64's
    # '+' and '/'.  The reference maps '+' and '/' inputs to '@' and feeds a
    # lenient base64 decoder; commons-codec decodeBase64 simply skips
    # non-alphabet characters.  Translate '@' -> '+' and keep '-' -> '/'... the
    # reference's decoder treats '-' via its url-safe table.
    translated = unique_id.replace("+", "@").replace("/", "@")
    # commons-codec decodeBase64 supports BOTH standard and url-safe alphabets
    # and SKIPS illegal characters ('@' is illegal and is dropped).
    std = []
    for c in translated:
        if c.isalnum() or c in "+/=":
            std.append(c)
        elif c == "-":
            std.append("+")
        elif c == "_":
            std.append("/")
        # '@' and anything else: skipped
    data = "".join(std)
    data += "=" * (-len(data) % 4)
    try:
        return base64.b64decode(data)
    except Exception:  # noqa: BLE001
        return None


class ModUniqueIdDissector(Dissector):
    INPUT_TYPE = "MOD_UNIQUE_ID"

    def __init__(self):
        self.wanted: Set[str] = set()

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return [
            "TIME.EPOCH:epoch",
            "IP:ip",
            "PROCESSID:processid",
            "COUNTER:counter",
            "THREAD_INDEX:threadindex",
        ]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        if name in ("epoch", "ip", "processid", "counter", "threadindex"):
            self.wanted.add(name)
            return STRING_OR_LONG
        return NO_CASTS

    def get_new_instance(self) -> "Dissector":
        return ModUniqueIdDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return

        raw = _decode_to_bytes(value)
        if raw is None or len(raw) != 18:
            return

        if "epoch" in self.wanted:
            timestamp = int.from_bytes(raw[0:4], "big") * 1000
            parsable.add_dissection(input_name, "TIME.EPOCH", "epoch", timestamp)
        if "ip" in self.wanted:
            ip_str = ".".join(str(b) for b in raw[4:8])
            parsable.add_dissection(input_name, "IP", "ip", ip_str)
        if "processid" in self.wanted:
            parsable.add_dissection(
                input_name, "PROCESSID", "processid", int.from_bytes(raw[8:12], "big")
            )
        if "counter" in self.wanted:
            parsable.add_dissection(
                input_name, "COUNTER", "counter", int.from_bytes(raw[12:14], "big")
            )
        if "threadindex" in self.wanted:
            parsable.add_dissection(
                input_name,
                "THREAD_INDEX",
                "threadindex",
                int.from_bytes(raw[14:18], "big"),
            )
