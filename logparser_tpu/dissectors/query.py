"""Query-string dissection: ``HTTP.QUERYSTRING`` -> ``STRING:*`` per parameter.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/QueryStringFieldDissector.java:
split on ``&``, then ``=``; parameter names lowercased; values url-decoded with
the resilient decoder (:76-108); invalid encodings fail the line.
"""
from __future__ import annotations

from typing import FrozenSet, List, Set

from ..core.casts import Cast, STRING_ONLY
from ..core.dissector import Dissector, extract_field_name
from ..core.exceptions import DissectionFailure
from .utils import resilient_url_decode


class QueryStringFieldDissector(Dissector):
    INPUT_TYPE = "HTTP.QUERYSTRING"

    def __init__(self):
        self.requested: Set[str] = set()
        self.want_all = False

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return ["STRING:*"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested.add(extract_field_name(input_name, output_name))
        return STRING_ONLY

    def prepare_for_run(self) -> None:
        self.want_all = "*" in self.requested

    def get_new_instance(self) -> "Dissector":
        return QueryStringFieldDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return

        for part in value.split("&"):
            equal_pos = part.find("=")
            if equal_pos == -1:
                if part != "":
                    name = part.lower()
                    if self.want_all or name in self.requested:
                        parsable.add_dissection(input_name, "STRING", name, "")
            else:
                name = part[:equal_pos].lower()
                if self.want_all or name in self.requested:
                    try:
                        parsable.add_dissection(
                            input_name,
                            "STRING",
                            name,
                            resilient_url_decode(part[equal_pos + 1 :]),
                        )
                    except ValueError as e:
                        # Invalid encoding in the line.
                        raise DissectionFailure(str(e)) from e
