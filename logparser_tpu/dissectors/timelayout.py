"""Compiled timestamp layouts: a small, serializable parse program for
fixed-layout timestamps.

This replaces the reference's java.time ``DateTimeFormatter`` machinery
(TimeStampDissector.java:404-424 builds a formatter from a Java pattern;
StrfTimeToDateTimeFormatter.java maps strftime).  A layout is a flat list of
items, each matching a fixed or narrow-variable slice of the input — exactly
the property that makes timestamp parsing vectorizable on TPU (every item
becomes a fixed gather + arithmetic once the layout is known).

Two front-ends compile to this representation:
- :func:`compile_java_pattern` — the subset of java.time pattern letters the
  reference uses (dd/MMM/yyyy:HH:mm:ss ZZ and friends).
- ``logparser_tpu.dissectors.strftime_stamp.compile_strftime`` — strftime.
"""
from __future__ import annotations

import datetime as _dt
import re
from typing import List, Optional, Tuple

MONTHS_SHORT = ["Jan", "Feb", "Mar", "Apr", "May", "Jun",
                "Jul", "Aug", "Sep", "Oct", "Nov", "Dec"]
MONTHS_FULL = ["January", "February", "March", "April", "May", "June",
               "July", "August", "September", "October", "November", "December"]
DAYS_SHORT = ["Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun"]
DAYS_FULL = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]


class LocaleData:
    """Month/weekday name tables + week rule for one locale.

    The reference's ``TimeStampDissector.setLocale`` threads a
    ``java.util.Locale`` into its DateTimeFormatter
    (TimeStampDissector.java:73-78, :106) and into
    ``WeekFields.of(locale)`` for the LOCAL week outputs (:455-459; the
    ``_utc`` twins stay WeekFields.ISO, :519-523).  These tables mirror
    the CLDR data Java's formatter resolves (JDK 9+ default): note the
    trailing periods in e.g. French/Dutch abbreviated month names.
    ``week_first_day`` is ISO numbering (1=Monday .. 7=Sunday)."""

    __slots__ = ("tag", "months_short", "months_full", "days_short",
                 "days_full", "ampm", "week_first_day", "week_min_days")

    def __init__(self, tag, months_short, months_full, days_short, days_full,
                 ampm=("AM", "PM"), week_first_day=1, week_min_days=4):
        self.tag = tag
        self.months_short = months_short
        self.months_full = months_full
        self.days_short = days_short
        self.days_full = days_full
        self.ampm = ampm
        self.week_first_day = week_first_day
        self.week_min_days = week_min_days


_EN = LocaleData("en", MONTHS_SHORT, MONTHS_FULL, DAYS_SHORT, DAYS_FULL)


def _load_locales() -> dict:
    """LOCALES from the CLDR-generated data file (cldr_names.json,
    produced by tools/cldr_import.py from Babel's vendored CLDR — adding
    a locale is a one-line edit there plus a regeneration run).  The
    checked-in JSON is the runtime source of truth; a missing file
    degrades to the built-in English tables."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "cldr_names.json")
    out = {"en": _EN, "en_gb": _EN, "en_uk": _EN}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):  # pragma: no cover - packaging error
        return out
    for tag, d in data.items():
        out[tag] = LocaleData(
            tag,
            list(d["months_short"]), list(d["months_full"]),
            list(d["days_short"]), list(d["days_full"]),
            ampm=tuple(d["ampm"]),
            week_first_day=int(d["week_first_day"]),
            week_min_days=int(d["week_min_days"]),
        )
    return out


LOCALES = _load_locales()


def week_based_fields(
    year: int, month: int, day: int, first_day: int = 1, min_days: int = 4
) -> Tuple[int, int]:
    """(week_based_year, week_of_week_based_year) per java.time
    ``WeekFields.of(locale)`` (ComputedDayOfField.localizedWeekOfWeekBasedYear
    semantics).  ``first_day``/``min_days`` default to ISO (Monday, 4) —
    then this agrees with ``datetime.date.isocalendar`` exactly."""
    date = _dt.date(year, month, day)
    dow = (date.isoweekday() - first_day) % 7 + 1
    doy = date.timetuple().tm_yday

    def sow_offset(d, w):
        week_start = (d - w) % 7
        return 7 - week_start if week_start + 1 > min_days else -week_start

    offset = sow_offset(doy, dow)
    week = (7 + offset + doy - 1) // 7
    if week == 0:
        # End-of-week of the previous week-based year.
        prev_len = (_dt.date(year, 1, 1) - _dt.date(year - 1, 1, 1)).days
        doy2 = doy + prev_len
        week = (7 + sow_offset(doy2, dow) + doy2 - 1) // 7
        return year - 1, week
    if week > 50:
        year_len = (_dt.date(year + 1, 1, 1) - _dt.date(year, 1, 1)).days
        new_year_week = (7 + offset + year_len + min_days - 1) // 7
        if week >= new_year_week:
            return year + 1, week - new_year_week + 1
    return year, week


def get_locale(tag: Optional[str]) -> LocaleData:
    """Resolve a locale tag ("fr", "fr_FR", "en-US") to its table.

    Unknown locales fall back to the English root tables with ISO weeks —
    the same graceful degradation as Java resolving missing CLDR data
    through the root locale."""
    if not tag:
        return _EN
    norm = tag.strip().lower().replace("-", "_")
    got = LOCALES.get(norm)
    if got is None:
        got = LOCALES.get(norm.split("_")[0], _EN)
    return got

# Curated zone-abbreviation table for %Z-style zone text (Java resolves these
# through its locale zone-name tables; we map to tzdata zones/fixed offsets).
_ZONE_ABBREVIATIONS = {
    "UTC": "UTC", "GMT": "UTC", "Z": "UTC", "UT": "UTC",
    "CET": "CET", "CEST": "CET", "MET": "MET", "MEST": "MET",
    "WET": "WET", "WEST": "WET", "EET": "EET", "EEST": "EET",
    "EST": "EST5EDT", "EDT": "EST5EDT",
    "CST": "CST6CDT", "CDT": "CST6CDT",
    "MST": "MST7MDT", "MDT": "MST7MDT",
    "PST": "PST8PDT", "PDT": "PST8PDT",
}

_ZONE_FULL_NAMES = {
    "UTC": "Coordinated Universal Time",
    "CET": "Central European Time",
    "MET": "Middle Europe Time",
    "WET": "Western European Time",
    "EET": "Eastern European Time",
    "EST5EDT": "Eastern Time",
    "CST6CDT": "Central Time",
    "MST7MDT": "Mountain Time",
    "PST8PDT": "Pacific Time",
}


class TimestampParseError(ValueError):
    """Raised when an input does not match the compiled layout."""


# A layout item is a tuple whose first element is the kind:
#   ("lit", text)
#   ("num", field, min_width, max_width, space_padded: bool)
#   ("text", field, style)          field: monthname|dayname|ampm
#   ("offset",)                     +HHMM / -HHMM  (+0000 for zero)
#   ("offset_colon",)               +HH:MM, 'Z' accepted for zero (pattern XXX)
#   ("zonetext",)                   zone abbreviation or region id
Item = Tuple


class ParsedTimestamp:
    """Resolved timestamp: local wall-clock fields + zone + epoch."""

    __slots__ = (
        "year", "month", "day", "hour", "minute", "second", "nano",
        "offset_seconds", "zone_name", "epoch_millis", "_dt_local",
    )

    def __init__(self, year, month, day, hour, minute, second, nano,
                 offset_seconds, zone_name, epoch_millis):
        self.year = year
        self.month = month
        self.day = day
        self.hour = hour
        self.minute = minute
        self.second = second
        self.nano = nano
        self.offset_seconds = offset_seconds
        self.zone_name = zone_name  # tzdata id when parsed from zone text
        self.epoch_millis = epoch_millis
        self._dt_local = _dt.date(year, month, day)

    # -- derived fields used by TimeStampDissector ----------------------

    def iso_week(self) -> int:
        return self._dt_local.isocalendar()[1]

    def iso_weekyear(self) -> int:
        return self._dt_local.isocalendar()[0]

    def monthname(self) -> str:
        return MONTHS_FULL[self.month - 1]

    def date_str(self) -> str:
        return f"{self.year:04d}-{self.month:02d}-{self.day:02d}"

    def time_str(self) -> str:
        return f"{self.hour:02d}:{self.minute:02d}:{self.second:02d}"

    def zone_display_name(self) -> str:
        """Java ZonedDateTime.getZone().getDisplayName(FULL, locale)."""
        if self.zone_name is not None:
            return _ZONE_FULL_NAMES.get(self.zone_name, self.zone_name)
        total = self.offset_seconds
        if total == 0:
            return "Z"
        sign = "+" if total >= 0 else "-"
        total = abs(total)
        h, rem = divmod(total, 3600)
        m, s = divmod(rem, 60)
        if s:
            return f"{sign}{h:02d}:{m:02d}:{s:02d}"
        return f"{sign}{h:02d}:{m:02d}"

    def as_utc(self) -> "_dt.datetime":
        return _dt.datetime.fromtimestamp(
            self.epoch_millis / 1000.0, tz=_dt.timezone.utc
        ).replace(microsecond=0) + _dt.timedelta(
            microseconds=(self.epoch_millis % 1000) * 1000
        )

    def utc_fields(self) -> "ParsedTimestamp":
        """The same instant re-expressed in UTC."""
        epoch_s, milli = divmod(self.epoch_millis, 1000)
        u = _dt.datetime.fromtimestamp(epoch_s, tz=_dt.timezone.utc)
        sub_nano = self.nano % 1_000_000  # keep micro/nano precision
        return ParsedTimestamp(
            u.year, u.month, u.day, u.hour, u.minute, u.second,
            milli * 1_000_000 + sub_nano,
            0, None, self.epoch_millis,
        )


_ZONE_RESOLVE_CACHE: dict = {}


def _resolve_zone_cached(name: str) -> Optional[str]:
    """%Z zone text -> tzdata id (None = unknown): abbreviation table +
    ZoneInfo validation, memoized — the validation was per-line cost on
    zone-text layouts and the distinct-name population is tiny."""
    got = _ZONE_RESOLVE_CACHE.get(name)
    if got is not None or name in _ZONE_RESOLVE_CACHE:
        return got
    zone: Optional[str] = _ZONE_ABBREVIATIONS.get(name.upper(), name)
    try:
        from zoneinfo import ZoneInfo

        ZoneInfo(zone)
    except Exception:
        zone = None
    if len(_ZONE_RESOLVE_CACHE) > 4096:  # hostile-corpus bound
        _ZONE_RESOLVE_CACHE.clear()
    _ZONE_RESOLVE_CACHE[name] = zone
    return zone


class TimeLayout:
    """A compiled, serializable timestamp layout."""

    def __init__(self, items: List[Item], default_zone: Optional[str] = None,
                 locale: Optional[LocaleData] = None):
        self.items = items
        # tzdata id applied when the layout itself carries no zone
        # (StrfTimeToDateTimeFormatter.java:97-105 defaults likewise).
        self.default_zone = default_zone
        # Month/day name tables (TimeStampDissector.setLocale semantics).
        self.locale = locale or _EN
        self._fast = None          # lazily compiled regex fast path
        self._fast_tried = False
        self._fixed = None         # lazily compiled fixed-width direct lane
        self._fixed_tried = False

    def with_locale(self, locale: LocaleData) -> "TimeLayout":
        """The same layout re-bound to another locale's name tables."""
        return TimeLayout(self.items, self.default_zone, locale)

    def __getstate__(self):
        state = self.__dict__.copy()
        # Compiled lanes hold closures/patterns; rebuild lazily on load.
        state["_fast"] = None
        state["_fast_tried"] = False
        state["_fixed"] = None
        state["_fixed_tried"] = False
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.__dict__.setdefault("_fixed", None)
        self.__dict__.setdefault("_fixed_tried", False)

    def has_zone(self) -> bool:
        return any(it[0] in ("offset", "offset_colon", "zonetext") for it in self.items)

    # -- parsing ---------------------------------------------------------

    def _compile_fast(self):
        """One anchored regex for fixed-width layouts (the hot shapes).
        Returns (pattern, extractors) or None when any item is variable
        width — regex backtracking could then accept inputs the greedy
        item-by-item parser rejects, so those layouts keep the slow path.
        """
        parts: List[str] = []
        extractors: List = []  # (kind, field_or_table)
        last_index = len(self.items) - 1
        for i, it in enumerate(self.items):
            kind = it[0]
            if kind == "lit":
                parts.append(re.escape(it[1]))
            elif kind == "num":
                _, field, minw, maxw, space_pad = it
                if space_pad or minw != maxw:
                    return None
                parts.append(f"(\\d{{{minw}}})")
                extractors.append(("num", field))
            elif kind == "text":
                _, field, style = it
                if field == "monthname":
                    table = (self.locale.months_full if style == "full"
                             else self.locale.months_short)
                    key = "month"
                elif field == "dayname":
                    table = (self.locale.days_full if style == "full"
                             else self.locale.days_short)
                    key = "dayofweek"
                else:
                    table = list(self.locale.ampm)
                    key = "ampm"
                alts = sorted(table, key=len, reverse=True)
                parts.append("(" + "|".join(re.escape(a) for a in alts) + ")")
                extractors.append(("text", (key, [a.lower() for a in table])))
            elif kind == "offset":
                parts.append(r"([+-]\d{2}:?\d{2})")
                extractors.append(("offset", None))
            elif kind == "offset_colon":
                parts.append(r"(Z|[+-]\d{2}:\d{2})")
                extractors.append(("offset", None))
            elif kind == "zonetext" and i == last_index:
                # Positional check, NOT identity: ("zonetext",) literals
                # are constant-folded to one shared tuple, so a layout
                # with two %Z items would pass an `is` test mid-layout.
                # Zone text as the FINAL item only: the group is greedy
                # over the same charset the slow parser uses and nothing
                # follows it, so regex backtracking cannot accept an
                # input the item-by-item parser rejects.  Zone names
                # resolve through a cache (abbreviation table + ZoneInfo
                # validation were ~a third of the per-line cost).
                parts.append(r"([A-Za-z_/+\-0-9]+)")
                extractors.append(("zonetext", None))
            else:  # mid-layout zone text stays on the slow path
                return None
        return re.compile("".join(parts) + r"\Z", re.IGNORECASE), extractors

    def _compile_fixed(self):
        """Direct-slicing lane for fully fixed-width offset-bearing layouts
        (the Apache ``dd/MMM/yyyy:HH:mm:ss ZZ`` shape): no regex, no field
        dict, no datetime objects in the epoch math.  Returns a closure
        ``s -> ParsedTimestamp | None`` (None = fall through to the exact
        slower lanes, which also own every error message), or None when the
        layout has any variable-width / zone-text / week / 12h construct.

        Bit-exactness notes: the epoch replicates ``datetime.timestamp()``'s
        float rounding exactly (``int((total_us / 10**6) * 1000)`` — the
        same single division + multiply), the leap-second clamp matches
        _resolve, and any out-of-range component bails to the slow lane so
        range errors surface with identical messages.
        """
        steps = []  # (start, end, kind, payload); fixed offsets into s
        pos = 0
        have = set()
        for it in self.items:
            kind = it[0]
            if kind == "lit":
                steps.append((pos, pos + len(it[1]), "lit", it[1].lower()))
                pos += len(it[1])
            elif kind == "num":
                _, field, minw, maxw, space_pad = it
                if space_pad or minw != maxw:
                    return None
                if field not in ("day", "month", "year", "hour", "minute",
                                 "second", "milli"):
                    return None
                steps.append((pos, pos + minw, "num", field))
                have.add(field)
                pos += minw
            elif kind == "text":
                _, field, style = it
                if field != "monthname":
                    return None
                table = (self.locale.months_full if style == "full"
                         else self.locale.months_short)
                widths = {len(t) for t in table}
                if len(widths) != 1:
                    return None
                w = widths.pop()
                lookup = {t.lower(): i + 1 for i, t in enumerate(table)}
                if len(lookup) != len(table):
                    return None
                steps.append((pos, pos + w, "month_text", lookup))
                have.add("month")
                pos += w
            elif kind == "offset":
                steps.append((pos, pos + 5, "offset", None))
                have.add("offset")
                pos += 5
            else:
                return None
        if not {"year", "month", "day", "offset"} <= have:
            return None
        total = pos

        # The steps are layout-static, so the lane is source-generated:
        # straight-line slicing + the exact epoch math, no per-item
        # dispatch loop (the loop + if-chain was ~a fifth of the compiled
        # oracle's per-line cost).  Operations are IDENTICAL to the old
        # interpreted loop — same rounding, same clamps, same bails.
        field_var = {"day": "d", "month": "mo", "year": "y", "hour": "h",
                     "minute": "mi", "second": "sec", "milli": "milli"}
        ns: dict = {"_PT": ParsedTimestamp}
        src = [
            "def run(s):",
            f"    if len(s) != {total}:",
            "        return None",
            "    y = mo = d = h = mi = sec = milli = off = 0",
            "    try:",
        ]

        def emit(line):
            src.append("        " + line)

        for j, (a, b, kind, payload) in enumerate(steps):
            if kind == "lit":
                emit(f"if s[{a}:{b}].lower() != {payload!r}:")
                emit("    return None")
            elif kind == "num":
                emit(f"part = s[{a}:{b}]")
                emit("if not part.isdigit():")
                emit("    return None")
                emit(f"{field_var[payload]} = int(part)")
            elif kind == "month_text":
                ns[f"_lk{j}"] = payload
                emit(f"mo = _lk{j}.get(s[{a}:{b}].lower(), 0)")
                emit("if mo == 0:")
                emit("    return None")
            else:  # offset
                emit(f"sign = s[{a}]")
                emit(f"body = s[{a + 1}:{b}]")
                # Strict ASCII digits: the slower lanes' offset regex is
                # [0-9] (unlike the unicode-accepting isdigit() the
                # numeric fields share with them).
                emit('if (sign not in "+-" or not body.isascii()'
                     " or not body.isdigit()):")
                emit("    return None")
                emit("off = int(body[:2]) * 3600 + int(body[2:]) * 60")
                # datetime.timezone (the slow lane) rejects offsets of
                # 24h or more — bail so it does.
                emit("if off >= 86400:")
                emit("    return None")
                emit('if sign == "-":')
                emit("    off = -off")
        src += [
            "        if sec == 60:",
            "            sec = 59  # leap second: java.time SMART clamps",
            "        if not (1 <= mo <= 12 and 1 <= d <= 31 and h <= 23",
            "                and mi <= 59 and sec <= 59):",
            "            return None",
            "        # days-from-civil (proleptic Gregorian), then the exact",
            "        # float rounding datetime.timestamp() applies.",
            "        yy = y - (mo <= 2)",
            "        era = (yy if yy >= 0 else yy - 399) // 400",
            "        yoe = yy - era * 400",
            "        doy = (153 * (mo + (-3 if mo > 2 else 9)) + 2) // 5 + d - 1",
            "        doe = yoe * 365 + yoe // 4 - yoe // 100 + doy",
            "        days = era * 146097 + doe - 719468",
            "        base_s = days * 86400 + h * 3600 + mi * 60 + sec - off",
            "        micro = milli * 1000",
            "        total_us = base_s * 10**6 + micro",
            "        epoch_millis = int((total_us / 10**6) * 1000)",
            "        return _PT(",
            "            y, mo, d, h, mi, sec, milli * 1_000_000, off, None,",
            "            epoch_millis,",
            "        )",
            "    except (ValueError, IndexError):",
            "        return None",
        ]
        exec(  # noqa: S102 — our own generated source
            compile("\n".join(src) + "\n", "<timelayout-fixed>", "exec"), ns
        )
        return ns["run"]

    def parse(self, s: str) -> ParsedTimestamp:
        if not self._fixed_tried:
            self._fixed_tried = True
            self._fixed = self._compile_fixed()
        if self._fixed is not None:
            ts = self._fixed(s)
            if ts is not None:
                return ts
        if not self._fast_tried:
            self._fast_tried = True
            self._fast = self._compile_fast()
        if self._fast is not None:
            m = self._fast[0].match(s)
            if m is not None:
                fields: dict = {}
                for (kind, spec), group in zip(self._fast[1], m.groups()):
                    if kind == "num":
                        fields[spec] = int(group)
                    elif kind == "text":
                        key, lowered = spec
                        idx = lowered.index(group.lower())
                        fields[key] = idx + 1 if key == "month" else idx
                    elif kind == "zonetext":
                        zone = _resolve_zone_cached(group)
                        if zone is None:
                            raise TimestampParseError(
                                f"Text '{s}' could not be parsed: "
                                f"unknown zone '{group}'"
                            )
                        fields["zone"] = zone
                    else:  # offset
                        if group in ("Z", "z"):
                            fields["offset"] = 0
                        else:
                            sign = -1 if group[0] == "-" else 1
                            hh = int(group[1:3])
                            mm = int(group[-2:])
                            fields["offset"] = sign * (hh * 3600 + mm * 60)
                return self._resolve(fields, s)
            # fall through: the item-by-item parser produces the exact
            # error message (index of the first mismatch)
        return self._parse_slow(s)

    def _parse_slow(self, s: str) -> ParsedTimestamp:
        fields = {}
        pos = 0
        n = len(s)
        for it in self.items:
            kind = it[0]
            if kind == "lit":
                lit = it[1]
                if s[pos : pos + len(lit)].lower() != lit.lower():
                    raise TimestampParseError(
                        f"Text '{s}' could not be parsed at index {pos}"
                    )
                pos += len(lit)
            elif kind == "num":
                _, field, minw, maxw, space_pad = it
                start = pos
                if space_pad:
                    while pos < n and s[pos] == " " and pos - start < maxw - 1:
                        pos += 1
                digits_start = pos
                signed = field == "epoch" and pos < n and s[pos] in "+-"
                if signed:
                    pos += 1
                while pos < n and s[pos].isdigit() and (pos - digits_start) < maxw:
                    pos += 1
                ndig = pos - digits_start - (1 if signed else 0)
                if (ndig < minw and not space_pad) or ndig == 0:
                    raise TimestampParseError(
                        f"Text '{s}' could not be parsed at index {start}"
                    )
                # The slice keeps any leading sign; int() applies it.
                fields[field] = int(s[digits_start:pos])
            elif kind == "text":
                _, field, style = it
                pos = self._parse_text(s, pos, field, style, fields)
            elif kind == "offset":
                pos = self._parse_offset(s, pos, fields, colon=False)
            elif kind == "offset_colon":
                pos = self._parse_offset(s, pos, fields, colon=True)
            elif kind == "zonetext":
                pos = self._parse_zonetext(s, pos, fields)
            else:  # pragma: no cover
                raise AssertionError(kind)
        if pos != n:
            raise TimestampParseError(
                f"Text '{s}' could not be parsed, unparsed text found at index {pos}"
            )
        return self._resolve(fields, s)

    def _parse_text(self, s, pos, field, style, fields) -> int:
        if field == "monthname":
            table = (self.locale.months_full if style == "full"
                     else self.locale.months_short)
            key = "month"
        elif field == "dayname":
            table = (self.locale.days_full if style == "full"
                     else self.locale.days_short)
            key = "dayofweek"
        else:  # ampm
            table = (list(self.locale.ampm) if style == "upper"
                     else [a.lower() for a in self.locale.ampm])
            key = "ampm"
        low = s[pos:].lower()
        for idx, name in enumerate(table):
            if low.startswith(name.lower()):
                fields[key] = idx + 1 if key == "month" else idx
                return pos + len(name)
        raise TimestampParseError(f"Text '{s}' could not be parsed at index {pos}")

    def _parse_offset(self, s, pos, fields, colon: bool) -> int:
        if colon and pos < len(s) and s[pos] in "zZ":
            fields["offset"] = 0
            return pos + 1
        m = re.match(r"([+-])([0-9]{2}):?([0-9]{2})", s[pos:])
        if not m:
            raise TimestampParseError(f"Text '{s}' could not be parsed at index {pos}")
        sign = -1 if m.group(1) == "-" else 1
        fields["offset"] = sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60)
        return pos + m.end()

    def _parse_zonetext(self, s, pos, fields) -> int:
        m = re.match(r"[A-Za-z_/+\-0-9]+", s[pos:])
        if not m:
            raise TimestampParseError(f"Text '{s}' could not be parsed at index {pos}")
        name = m.group(0)
        zone = _resolve_zone_cached(name)
        if zone is None:
            raise TimestampParseError(
                f"Text '{s}' could not be parsed: unknown zone '{name}'"
            )
        fields["zone"] = zone
        return pos + m.end()

    # -- resolution ------------------------------------------------------

    def _resolve(self, fields: dict, original: str) -> ParsedTimestamp:
        zone_name = fields.get("zone")
        offset = fields.get("offset")
        if zone_name is None and offset is None and self.default_zone is not None:
            zone_name = self.default_zone

        if "epoch" in fields:
            epoch_s = fields["epoch"]
            epoch_millis = epoch_s * 1000
            off = offset if offset is not None else 0
            tz = _dt.timezone(_dt.timedelta(seconds=off))
            local = _dt.datetime.fromtimestamp(epoch_s, tz=tz)
            return ParsedTimestamp(
                local.year, local.month, local.day, local.hour, local.minute,
                local.second, 0, off, zone_name if offset is None else None,
                epoch_millis,
            )

        year = fields.get("year")
        if year is None and "year2" in fields:
            year = 2000 + fields["year2"]
        if year is None and "wby" in fields and "isoweek" in fields:
            # Week-based date (%G/%V/%u)
            wby = fields["wby"]
            week = fields["isoweek"]
            dow = fields.get("isodow", 1)
            d = _dt.date.fromisocalendar(wby, week, dow)
            year, month, day = d.year, d.month, d.day
        else:
            month = fields.get("month")
            day = fields.get("day")
            if year is not None and month is None and "doy" in fields:
                d = _dt.date(year, 1, 1) + _dt.timedelta(days=fields["doy"] - 1)
                month, day = d.month, d.day

        if year is None or month is None or day is None:
            raise TimestampParseError(
                f"Unable to obtain a complete date from '{original}'"
            )

        hour = fields.get("hour")
        if hour is None and "clock_hour" in fields:
            ch = fields["clock_hour"]
            if ch in (0, 24):
                # Java's SMART resolver special-cases BOTH 0 and 24 for
                # CLOCK_HOUR_OF_DAY as midnight (jdk Parsed.resolveTimeLenient
                # accepts 0 explicitly in SMART mode) — so `%H` parsing of
                # "00:xx:xx" succeeds in the reference.
                hour = 0
            elif 1 <= ch <= 23:
                hour = ch
            else:
                raise TimestampParseError(
                    f"Invalid value for ClockHourOfDay: {ch} in '{original}'"
                )
        if hour is None and "hour12" in fields:
            h12 = fields["hour12"]
            ampm = fields.get("ampm", 0)
            hour = (h12 % 12) + (12 if ampm == 1 else 0)
        if hour is None:
            hour = 0
        minute = fields.get("minute", 0)
        second = fields.get("second", 0)
        nano = fields.get("milli", 0) * 1_000_000 + fields.get("micro", 0) * 1_000

        if second == 60:  # leap second: java.time SMART clamps
            second = 59

        local = _dt.datetime(year, month, day, hour, minute, second,
                             microsecond=nano // 1000)
        if zone_name is not None and offset is None:
            from zoneinfo import ZoneInfo

            tz = ZoneInfo(zone_name)
            aware = local.replace(tzinfo=tz, fold=0)
            epoch_millis = int(aware.timestamp() * 1000)
            real_offset = int(aware.utcoffset().total_seconds())
            return ParsedTimestamp(year, month, day, hour, minute, second, nano,
                                   real_offset, zone_name, epoch_millis)
        off = offset if offset is not None else 0
        tz = _dt.timezone(_dt.timedelta(seconds=off))
        aware = local.replace(tzinfo=tz)
        epoch_millis = int(aware.timestamp() * 1000)
        return ParsedTimestamp(year, month, day, hour, minute, second, nano,
                               off, None, epoch_millis)


# ---------------------------------------------------------------------------
# java.time pattern front-end (the subset the reference uses)
# ---------------------------------------------------------------------------

def compile_java_pattern(
    pattern: str,
    default_zone: Optional[str] = None,
    locale: Optional[LocaleData] = None,
) -> TimeLayout:
    """Compile the java.time pattern subset used by the reference:
    d/dd, M/MM/MMM/MMMM, y/yy/yyyy, H/HH, m/mm, s/ss, S/SSS, E/EEE/EEEE,
    Z/ZZ/ZZZ (+HHMM), X/XX/XXX (+HH:MM, Z), z (zone text), quoted literals.
    """
    items: List[Item] = []
    i = 0
    n = len(pattern)
    while i < n:
        c = pattern[i]
        if c.isalpha():
            j = i
            while j < n and pattern[j] == c:
                j += 1
            count = j - i
            if c == "d":
                items.append(("num", "day", count, 2, False))
            elif c == "M":
                if count >= 4:
                    items.append(("text", "monthname", "full"))
                elif count == 3:
                    items.append(("text", "monthname", "short"))
                else:
                    items.append(("num", "month", count, 2, False))
            elif c == "y":
                if count == 2:
                    items.append(("num", "year2", 2, 2, False))
                else:
                    items.append(("num", "year", count, 4, False))
            elif c == "H":
                items.append(("num", "hour", count, 2, False))
            elif c == "h":
                items.append(("num", "hour12", count, 2, False))
            elif c == "m":
                items.append(("num", "minute", count, 2, False))
            elif c == "s":
                items.append(("num", "second", count, 2, False))
            elif c == "S":
                items.append(("num", "milli", count, count, False))
            elif c == "E":
                items.append(("text", "dayname", "full" if count >= 4 else "short"))
            elif c == "a":
                items.append(("text", "ampm", "upper"))
            elif c == "Z":
                items.append(("offset",))
            elif c == "X":
                items.append(("offset_colon",))
            elif c == "z":
                items.append(("zonetext",))
            elif c == "T":  # bare T appears unquoted in some patterns
                items.append(("lit", "T"))
            else:
                raise ValueError(f"Unsupported pattern letter '{c}' in {pattern!r}")
            i = j
        elif c == "'":
            j = i + 1
            lit = []
            while j < n:
                if pattern[j] == "'":
                    if j + 1 < n and pattern[j + 1] == "'":
                        lit.append("'")
                        j += 2
                        continue
                    break
                lit.append(pattern[j])
                j += 1
            items.append(("lit", "".join(lit) if lit else "'"))
            i = j + 1
        else:
            items.append(("lit", c))
            i += 1

    # Merge adjacent literals for faster parsing.
    merged: List[Item] = []
    for it in items:
        if it[0] == "lit" and merged and merged[-1][0] == "lit":
            merged[-1] = ("lit", merged[-1][1] + it[1])
        else:
            merged.append(list(it) if it[0] == "lit" else it)
    merged = [tuple(it) if isinstance(it, list) else it for it in merged]
    return TimeLayout(merged, default_zone, locale)
