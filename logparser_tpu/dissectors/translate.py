"""Type-converter dissectors auto-inserted into the graph.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/translate/*.java:
1:1 type edges (same name, new type) built on SimpleDissector:
- ConvertCLFIntoNumber: '-' (or null) -> 0
- ConvertNumberIntoCLF: "0" -> null
- ConvertMillisecondsIntoMicroseconds: value * 1000
- ConvertSecondsWithMillisString: "1483455396.639" -> epoch millis
"""
from __future__ import annotations

from typing import List

from ..core.casts import STRING_OR_LONG
from ..core.dissector import Dissector, SimpleDissector
from ..core.fields import ParsedField


class TypeConvertBaseDissector(SimpleDissector):
    def __init__(self, input_type: str = None, output_type: str = None):
        outputs = {} if output_type is None else {output_type + ":": STRING_OR_LONG}
        super().__init__(input_type, outputs)
        self.output_type = output_type

    def get_new_instance(self) -> "Dissector":
        return type(self)(self._input_type, self.output_type)


class ConvertCLFIntoNumber(TypeConvertBaseDissector):
    def dissect_field(self, parsable, input_name: str, pf: ParsedField) -> None:
        s = pf.value.get_string()
        if s is None or s == "-":
            parsable.add_dissection(input_name, self.output_type, "", 0)
        else:
            parsable.add_dissection(input_name, self.output_type, "", pf.value)


class ConvertNumberIntoCLF(TypeConvertBaseDissector):
    def dissect_field(self, parsable, input_name: str, pf: ParsedField) -> None:
        if pf.value.get_string() == "0":
            parsable.add_dissection(input_name, self.output_type, "", None)
        else:
            parsable.add_dissection(input_name, self.output_type, "", pf.value)


class ConvertMillisecondsIntoMicroseconds(TypeConvertBaseDissector):
    def dissect_field(self, parsable, input_name: str, pf: ParsedField) -> None:
        parsable.add_dissection(
            input_name, self.output_type, "", pf.value.get_long() * 1000
        )


class ConvertSecondsWithMillisStringDissector(TypeConvertBaseDissector):
    def dissect_field(self, parsable, input_name: str, pf: ParsedField) -> None:
        seconds_str, _, millis_str = pf.value.get_string().partition(".")
        epoch = int(seconds_str) * 1000 + int(millis_str)
        parsable.add_dissection(input_name, self.output_type, "", epoch)
