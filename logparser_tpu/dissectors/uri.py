"""URI dissection with real-world repair.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/HttpUriDissector.java:
``HTTP.URI`` -> protocol/userinfo/host/port/path/query/ref (:52-63) after a
repair chain for garbage URIs (:111-199):

1. %-encode bad characters (control, space, unwise ``{}|\\^[]``` , ``<>"``)
   byte-wise over UTF-8, like commons-httpclient URIUtil.encode.
2. Normalize query separators: any '?' to '&', then the first '&' to '?&'.
3. Fix '%' signs that are not escape sequences (twice).
4. Repair almost-HTML-encoded entities and unescape HTML4.
5. Fix '=#' and '#&' artifacts; collapse multiple '#' to '~'.
6. Parse like java.net.URI (server-based authority or a null host), faking
   ``dummy-protocol://dummy.host.name`` for relative URIs.
"""
from __future__ import annotations

import html.entities
import re
from typing import FrozenSet, List, Optional, Set

from ..core.casts import Cast, NO_CASTS, STRING_ONLY, STRING_OR_LONG
from ..core.dissector import Dissector, extract_field_name
from ..core.exceptions import DissectionFailure

# Bytes that URIUtil.encode must escape: control, space, unwise, <>", 0xFF
# (HttpUriDissector.java:111-121 builds the allowed set; this is its complement).
# ENCODE_PRINTABLE is the printable subset the DEVICE tier models without the
# oracle (postproc.split_uri_fast / split_csr masks, arrow_bridge splice) —
# those masks are built from THIS constant so the device/host bit-exactness
# argument cannot drift when the set changes.
ENCODE_PRINTABLE = b' {}|\\^[]`<>"'
_ENCODE_BYTES = set(range(0x00, 0x20)) | {0x7F, 0xFF}
_ENCODE_BYTES |= set(ENCODE_PRINTABLE)

_BAD_ESCAPE_PATTERN = re.compile("%([^0-9a-fA-F]|[0-9a-fA-F][^0-9a-fA-F]|.$|$)")
_EQUALS_HASH_PATTERN = re.compile("=#")
_HASH_AMP_PATTERN = re.compile("#&")
_DOUBLE_HASH_PATTERN = re.compile("#(.*)#")
_ALMOST_HTML_ENCODED = re.compile("([^&])(#x[0-9a-fA-F][0-9a-fA-F];)")

_URI_SPLIT = re.compile(
    r"^(?:([^:/?#]+):)?(?://([^/?#]*))?([^?#]*)(?:\?([^#]*))?(?:#(.*))?$"
)
_SCHEME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9+.\-]*$")
_HOST_RE = re.compile(r"^[A-Za-z0-9.\-]*$")

_NUMERIC_ENTITY = re.compile(r"&#(?:[xX]([0-9a-fA-F]+)|([0-9]+));")
_NAMED_ENTITY = re.compile(r"&([a-zA-Z][a-zA-Z0-9]*);")


# Fast-path gate for _encode_bad_uri_chars: any char that is non-ASCII
# (multi-byte under UTF-8) or in the encode set takes the byte loop;
# everything else is the identity.
_NEEDS_ENCODE_RE = re.compile(
    "[" + re.escape("".join(chr(b) for b in sorted(_ENCODE_BYTES)))
    + "\u0080-\U0010ffff]"
)


def _encode_bad_uri_chars(s: str) -> str:
    if _NEEDS_ENCODE_RE.search(s) is None:
        # Pure-ASCII input with no escapable byte: the byte loop below is
        # the identity (every byte maps to chr(byte)).
        return s
    out = []
    for b in s.encode("utf-8"):
        if b in _ENCODE_BYTES:
            out.append("%%%02X" % b)
        else:
            out.append(chr(b))
    # Re-interpret the remaining raw bytes as latin-1 passthrough; join keeps
    # high bytes as single chars, matching the Java byte-wise behavior.
    return "".join(out)


def _unescape_html4(s: str) -> str:
    """commons-lang3 unescapeHtml4: named HTML4 entities + numeric entities,
    semicolon required."""
    if "&" not in s:
        return s

    def named(m: "re.Match[str]") -> str:
        repl = html.entities.entitydefs.get(m.group(1))
        return repl if repl is not None else m.group(0)

    def numeric(m: "re.Match[str]") -> str:
        code = int(m.group(1), 16) if m.group(1) is not None else int(m.group(2))
        if code > 0x10FFFF:
            return m.group(0)
        return chr(code)

    s = _NUMERIC_ENTITY.sub(numeric, s)
    s = _NAMED_ENTITY.sub(named, s)
    return s


def _percent_decode(s: str) -> str:
    """java.net.URI decode(): %XX runs -> bytes -> UTF-8 (replace on error)."""
    if "%" not in s:
        return s
    out = []
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if c == "%" and i + 2 < n + 1:
            run = bytearray()
            while i < n and s[i] == "%" and i + 2 < n:
                try:
                    run.append(int(s[i + 1 : i + 3], 16))
                except ValueError:
                    break
                i += 3
            if run:
                out.append(run.decode("utf-8", errors="replace"))
                continue
        out.append(c)
        i += 1
    return "".join(out)


class JavaUri:
    """Minimal java.net.URI equivalent: split + server-based authority parse."""

    __slots__ = ("scheme", "userinfo", "host", "port", "path", "raw_query", "fragment")

    def __init__(self, uri_string: str):
        m = _URI_SPLIT.match(uri_string)
        if m is None:  # the regex is total; kept for safety
            raise ValueError(f"Malformed URI: {uri_string!r}")
        scheme, authority, path, query, fragment = m.groups()

        if scheme is not None and not _SCHEME_RE.match(scheme):
            raise ValueError(f"Illegal character in scheme name: {uri_string!r}")
        for component in (path, query, fragment):
            if component and (" " in component or "#" in component):
                raise ValueError(f"Illegal character in URI: {uri_string!r}")

        self.scheme = scheme
        self.userinfo: Optional[str] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        if authority is not None:
            self._parse_authority(authority)
        self.path = _percent_decode(path) if path else ("" if authority is not None else path or "")
        self.raw_query = query
        self.fragment = _percent_decode(fragment) if fragment is not None else None

    def _parse_authority(self, authority: str) -> None:
        """Server-based parse; on failure the authority is registry-based and
        host/userinfo/port stay None (mirrors java.net.URI)."""
        rest = authority
        userinfo = None
        at = rest.rfind("@")
        if at != -1:
            userinfo = rest[:at]
            rest = rest[at + 1 :]
        host = rest
        port: Optional[int] = None
        if rest.startswith("["):  # IPv6 literal
            close = rest.find("]")
            if close == -1:
                return  # registry-based
            host = rest[: close + 1]
            tail = rest[close + 1 :]
            if tail.startswith(":") and tail[1:].isdigit():
                port = int(tail[1:])
            elif tail not in ("", ":"):
                return
        else:
            colon = rest.rfind(":")
            if colon != -1:
                port_str = rest[colon + 1 :]
                if port_str == "":
                    host = rest[:colon]
                elif port_str.isdigit():
                    host = rest[:colon]
                    port = int(port_str)
                else:
                    return  # not a valid port: registry-based
            if not _HOST_RE.match(host):
                return  # registry-based authority: host is null
        self.userinfo = _percent_decode(userinfo) if userinfo is not None else None
        self.host = host
        self.port = port


class HttpUriDissector(Dissector):
    INPUT_TYPE = "HTTP.URI"

    _FIELDS = {
        "protocol": STRING_ONLY,
        "userinfo": STRING_ONLY,
        "host": STRING_ONLY,
        "port": STRING_OR_LONG,
        "path": STRING_ONLY,
        "query": STRING_ONLY,
        "ref": STRING_ONLY,
    }

    def __init__(self):
        self.wanted: Set[str] = set()

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return [
            "HTTP.PROTOCOL:protocol",
            "HTTP.USERINFO:userinfo",
            "HTTP.HOST:host",
            "HTTP.PORT:port",
            "HTTP.PATH:path",
            "HTTP.QUERYSTRING:query",
            "HTTP.REF:ref",
        ]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        casts = self._FIELDS.get(name)
        if casts is None:
            return NO_CASTS
        self.wanted.add(name)
        return casts

    def get_new_instance(self) -> "Dissector":
        return HttpUriDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        original = field.value.get_string()
        if original is None or original == "":
            return

        uri_string = _encode_bad_uri_chars(original)

        # Normalize ?/& so the query string always starts with ?& .
        if "?" in uri_string or "&" in uri_string:
            uri_string = uri_string.replace("?", "&")
            uri_string = uri_string.replace("&", "?&", 1)

        # Fix % signs that are not escape sequences (twice: overlaps).
        # Presence gates: every pattern in this repair block requires its
        # trigger character, so clean URIs skip the regex passes.
        if "%" in uri_string:
            uri_string = _BAD_ESCAPE_PATTERN.sub(r"%25\1", uri_string)
            uri_string = _BAD_ESCAPE_PATTERN.sub(r"%25\1", uri_string)

        if "#" in uri_string:
            # Repair almost-HTML-encoded entities, then unescape HTML4.
            uri_string = _ALMOST_HTML_ENCODED.sub(r"\1&\2", uri_string)
            uri_string = _unescape_html4(uri_string)
            uri_string = _EQUALS_HASH_PATTERN.sub("=", uri_string)
            uri_string = _HASH_AMP_PATTERN.sub("&", uri_string)

            # Multiple '#': keep only the last as the fragment marker.
            while _DOUBLE_HASH_PATTERN.search(uri_string):
                uri_string = _DOUBLE_HASH_PATTERN.sub(r"~\1#", uri_string)
        else:
            uri_string = _unescape_html4(uri_string)

        is_url = True
        try:
            if uri_string[0] == "/":
                uri = JavaUri("dummy-protocol://dummy.host.name" + uri_string)
                is_url = False  # do not return the values we just faked
            else:
                uri = JavaUri(uri_string)
        except ValueError as e:
            raise DissectionFailure(
                f"Failed to parse URI >>{original}<< because of : {e}"
            ) from e

        w = self.wanted
        if "query" in w:
            parsable.add_dissection(
                input_name, "HTTP.QUERYSTRING", "query", uri.raw_query or ""
            )
        if "path" in w:
            parsable.add_dissection(input_name, "HTTP.PATH", "path", uri.path)
        if "ref" in w:
            parsable.add_dissection(input_name, "HTTP.REF", "ref", uri.fragment)

        if is_url:
            if "protocol" in w:
                parsable.add_dissection(
                    input_name, "HTTP.PROTOCOL", "protocol", uri.scheme
                )
            if "userinfo" in w:
                parsable.add_dissection(
                    input_name, "HTTP.USERINFO", "userinfo", uri.userinfo
                )
            if "host" in w:
                parsable.add_dissection(input_name, "HTTP.HOST", "host", uri.host)
            if "port" in w and uri.port is not None:
                parsable.add_dissection(input_name, "HTTP.PORT", "port", uri.port)
