"""Cookie dissection.

Rebuilds of:
- RequestCookieListDissector.java: ``HTTP.COOKIES`` -> ``HTTP.COOKIE:*``; split
  on ``"; "``, names trimmed + lowercased, values url-decoded (:77-111).
- ResponseSetCookieListDissector.java: ``HTTP.SETCOOKIES`` -> ``HTTP.SETCOOKIE:*``;
  split on ``", "`` with special handling for commas inside ``expires=``
  (:78-115).
- ResponseSetCookieDissector.java: one Set-Cookie value -> value/expires
  (STRING seconds + TIME.EPOCH millis)/path/domain/comment (:63-105).
  Divergence from the reference: its parseExpire only catches
  IllegalArgumentException, so a non-first-format expires date crashes the Java
  parse with an uncaught DateTimeParseException; we try all three formats and
  fall back to 0 (the reference's intended behavior).
"""
from __future__ import annotations

from typing import FrozenSet, List, Optional, Set

from ..core.casts import Cast, STRING_ONLY, STRING_OR_LONG
from ..core.dissector import Dissector, extract_field_name
from ..core.exceptions import DissectionFailure
from .timelayout import TimeLayout, TimestampParseError, compile_java_pattern
from .utils import resilient_url_decode


class RequestCookieListDissector(Dissector):
    INPUT_TYPE = "HTTP.COOKIES"

    def __init__(self):
        self.requested: Set[str] = set()
        self.want_all = False

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return ["HTTP.COOKIE:*"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested.add(extract_field_name(input_name, output_name))
        return STRING_ONLY

    def prepare_for_run(self) -> None:
        self.want_all = "*" in self.requested

    def get_new_instance(self) -> "Dissector":
        return RequestCookieListDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return

        for part in value.split("; "):
            equal_pos = part.find("=")
            if equal_pos == -1:
                if part != "":
                    name = part.strip().lower()  # just a name, no value
                    if self.want_all or name in self.requested:
                        parsable.add_dissection(input_name, "HTTP.COOKIE", name, "")
            else:
                name = part[:equal_pos].strip().lower()
                if self.want_all or name in self.requested:
                    the_value = part[equal_pos + 1 :].strip()
                    try:
                        parsable.add_dissection(
                            input_name,
                            "HTTP.COOKIE",
                            name,
                            resilient_url_decode(the_value),
                        )
                    except ValueError as e:
                        raise DissectionFailure(str(e)) from e


_SPLIT_BY = ", "
_MINIMAL_EXPIRES_LENGTH = len("expires=XXXXXXX")


def _http_cookie_names(header_value: str) -> List[str]:
    """Minimal java.net.HttpCookie.parse equivalent: the cookie name(s) in one
    Set-Cookie header value (the reference only uses the parsed name)."""
    value = header_value
    if value.lower().startswith("set-cookie2:"):
        value = value[len("set-cookie2:") :]
    elif value.lower().startswith("set-cookie:"):
        value = value[len("set-cookie:") :]
    first = value.split(";", 1)[0].strip()
    name = first.split("=", 1)[0].strip()
    if not name:
        raise ValueError("Empty cookie header string")
    return [name]


class ResponseSetCookieListDissector(Dissector):
    INPUT_TYPE = "HTTP.SETCOOKIES"

    def __init__(self):
        self.requested: Set[str] = set()
        self.want_all = False

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return ["HTTP.SETCOOKIE:*"]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested.add(extract_field_name(input_name, output_name))
        return STRING_ONLY

    def prepare_for_run(self) -> None:
        self.want_all = "*" in self.requested

    def get_new_instance(self) -> "Dissector":
        return ResponseSetCookieListDissector()

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return

        # A ', '-separated list, except the expires attribute may itself
        # contain ', ' — rejoin a part that ends inside expires=.
        parts = value.split(_SPLIT_BY)
        previous = ""
        for part in parts:
            expires_index = part.lower().find("expires=")
            if expires_index != -1 and len(part) - _MINIMAL_EXPIRES_LENGTH < expires_index:
                previous = part
                continue
            if previous:
                part = previous + _SPLIT_BY + part
                previous = ""
            try:
                names = _http_cookie_names(part)
            except ValueError:
                continue
            for cookie_name in names:
                name = cookie_name.lower()
                if self.want_all or name in self.requested:
                    parsable.add_dissection(input_name, "HTTP.SETCOOKIE", name, part)


class ResponseSetCookieDissector(Dissector):
    INPUT_TYPE = "HTTP.SETCOOKIE"

    _DATE_LAYOUTS: Optional[List[TimeLayout]] = None

    def __init__(self):
        self.requested: Set[str] = set()

    @classmethod
    def _date_layouts(cls) -> List[TimeLayout]:
        if cls._DATE_LAYOUTS is None:
            cls._DATE_LAYOUTS = [
                compile_java_pattern("EEE',' dd-MMM-yyyy HH:mm:ss z", "UTC"),
                compile_java_pattern("EEE',' dd MMM yyyy HH:mm:ss z", "UTC"),
                compile_java_pattern("EEE MMM dd yyyy HH:mm:ss 'GMT'Z", "UTC"),
            ]
        return cls._DATE_LAYOUTS

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self) -> List[str]:
        return [
            "STRING:value",
            "STRING:expires",
            "TIME.EPOCH:expires",
            "STRING:path",
            "STRING:domain",
            "STRING:comment",
        ]

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        self.requested.add(name)
        if name == "expires":
            return STRING_OR_LONG
        return STRING_ONLY

    def get_new_instance(self) -> "Dissector":
        return ResponseSetCookieDissector()

    @classmethod
    def parse_attrs(cls, value: str) -> dict:
        """One Set-Cookie value -> delivered attributes.  The single source
        of the per-cookie attribute semantics, shared by the per-line
        dissect below and the batch CSR materializer (tpu/batch.py):
        ``value`` = the first ';'-part's value; exact-lowercase attribute
        keys (ResponseSetCookieDissector.java:99-118 switch — "Expires" is
        ignored, matching the reference); ``expires`` in seconds (the
        backwards-compatible STRING form) plus ``expires_epoch`` millis;
        later duplicate attributes overwrite (record last-wins)."""
        out: dict = {}
        for i, raw_part in enumerate(value.split(";")):
            part = raw_part.strip()
            kv = part.split("=", 1)
            key = kv[0].strip()
            part_value = kv[1].strip() if len(kv) == 2 else ""
            if i == 0:
                out["value"] = part_value
            elif key == "expires":
                expires = cls._parse_expire(part_value)
                out["expires"] = expires // 1000
                out["expires_epoch"] = expires
            elif key in ("domain", "comment", "path"):
                out[key] = part_value
            # Anything else (incl. max-age) is ignored.
        return out

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        value = field.value.get_string()
        if value is None or value == "":
            return

        attrs = self.parse_attrs(value)
        if "value" in attrs:
            parsable.add_dissection(input_name, "STRING", "value", attrs["value"])
        if "expires" in attrs:
            parsable.add_dissection(input_name, "STRING", "expires", attrs["expires"])
            parsable.add_dissection(
                input_name, "TIME.EPOCH", "expires", attrs["expires_epoch"]
            )
        for key in ("domain", "comment", "path"):
            if key in attrs:
                parsable.add_dissection(input_name, "STRING", key, attrs[key])

    @classmethod
    def _parse_expire(cls, expire_string: str) -> int:
        for layout in cls._date_layouts():
            try:
                return layout.parse(expire_string).epoch_millis
            except (TimestampParseError, ValueError):
                continue
        return 0
