"""LogFormat -> token-list compiler and the host (oracle) regex executor.

Rebuild of the reference's tokenformat package
(httpdlog/httpdlog-parser/.../dissectors/tokenformat/):

- :class:`TokenParser` — one format-token definition mapping a literal token
  (e.g. ``%h``) to (output type/name/casts, value regex, priority, optional
  custom dissector).  Regex constant library ported from TokenParser.java:35-65.
- :class:`NamedTokenParser` — token pattern with a regex capture for the field
  *name* (e.g. ``%{referer}i`` -> ``request.header.referer``)
  (NamedTokenParser.java:59-93).
- :class:`ParameterizedTokenParser` — token whose parameter configures a custom
  dissector (e.g. ``%{%d/%b/%Y}t``); a unique TYPE per parameter via an MD5
  suffix so each distinct strftime format gets its own dissector instance
  (ParameterizedTokenParser.java:115-132).
- :class:`TokenFormatDissector` — scans the format with all TokenParsers, sorts
  by position, kicks overlapping/lower-prio duplicates, fills gaps with fixed
  strings (TokenFormatDissector.java:294-379), then compiles ONE anchored regex
  where only demanded tokens get capture groups (:179-213) and runs it per line
  (:243-275).

This host path is the bit-exactness oracle; the TPU batch path compiles the
same token list into a split program (logparser_tpu.tpu.program).
"""
from __future__ import annotations

import hashlib
import re
from typing import TYPE_CHECKING, FrozenSet, List, Optional, Sequence, Set

from ..core.casts import Cast
from ..core.casts import STRING_ONLY
from ..core.dissector import Dissector
from ..core.exceptions import DissectionFailure

if TYPE_CHECKING:  # pragma: no cover
    from ..core.parsable import Parsable
    from ..core.parser import Parser

# ---------------------------------------------------------------------------
# Regex constant library (TokenParser.java:35-65)
# ---------------------------------------------------------------------------
FORMAT_DIGIT = "[0-9]"
FORMAT_NUMBER = FORMAT_DIGIT + "+"
FORMAT_CLF_NUMBER = FORMAT_NUMBER + "|-"
FORMAT_HEXDIGIT = "[0-9a-fA-F]"
FORMAT_HEXNUMBER = FORMAT_HEXDIGIT + "+"
FORMAT_CLF_HEXNUMBER = FORMAT_HEXNUMBER + "|-"
FORMAT_NON_ZERO_NUMBER = "[1-9][0-9]*"
FORMAT_CLF_NON_ZERO_NUMBER = FORMAT_NON_ZERO_NUMBER + "|-"
FORMAT_EIGHT_BIT_DECIMAL = "(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?)"
FORMAT_IPV4 = "(?:" + FORMAT_EIGHT_BIT_DECIMAL + "\\.){3}" + FORMAT_EIGHT_BIT_DECIMAL
FORMAT_IPV6 = (
    ":?(?:" + FORMAT_HEXDIGIT + "{1,4}(?::|.)?){0,8}(?::|::)?(?:"
    + FORMAT_HEXDIGIT + "{1,4}(?::|.)?){0,8}"
)
FORMAT_IP = FORMAT_IPV4 + "|" + FORMAT_IPV6
FORMAT_CLF_IP = FORMAT_IP + "|-"
FORMAT_STRING = ".*?"
FORMAT_NO_SPACE_STRING = "[^\\s]*"
FIXED_STRING = "FIXED_STRING"
FORMAT_STANDARD_TIME_US = (
    "[0-3][0-9]/(?:[a-zA-Z][a-zA-Z][a-zA-Z])/[1-9][0-9][0-9][0-9]"
    ":[0-9][0-9]:[0-9][0-9]:[0-9][0-9] [\\+|\\-][0-9][0-9][0-9][0-9]"
)
FORMAT_STANDARD_TIME_ISO8601 = (
    "[1-9][0-9][0-9][0-9]-[0-1][0-9]-[0-3][0-9]T[0-9][0-9]:[0-9][0-9]"
    ":[0-9][0-9][\\+|\\-][0-9][0-9]:[0-9][0-9]"
)
FORMAT_NUMBER_DECIMAL = FORMAT_NUMBER + "\\." + FORMAT_NUMBER
FORMAT_NUMBER_OPTIONAL_DECIMAL = FORMAT_NUMBER + "(?:\\." + FORMAT_NUMBER + ")?"


class TokenOutputField:
    """Output descriptor (type, name, casts) with optional deprecation warning
    (TokenOutputField.java:58-73)."""

    __slots__ = ("type", "name", "casts", "deprecated_for", "_warned")

    def __init__(self, ftype: str, name: str, casts: FrozenSet[Cast]):
        self.type = ftype
        self.name = name
        self.casts = casts
        self.deprecated_for: Optional[str] = None
        self._warned = False

    def deprecate_for(self, replacement: str) -> "TokenOutputField":
        self.deprecated_for = replacement
        return self

    def was_used(self) -> None:
        if self.deprecated_for and not self._warned:
            self._warned = True
            import logging

            logging.getLogger(__name__).warning(
                "The field %s:%s is deprecated; use %s instead.",
                self.type,
                self.name,
                self.deprecated_for,
            )

    def __repr__(self) -> str:
        return f"{self.type}:{self.name}"


class Token:
    """One matched token instance within a LogFormat (Token.java:30-120)."""

    def __init__(self, regex: str, start_pos: int, length: int, prio: int):
        self.regex = regex
        self.start_pos = start_pos
        self.length = length
        self.prio = prio
        self.output_fields: List[TokenOutputField] = []
        self.custom_dissector: Optional[Dissector] = None
        self.warning_message_when_used: Optional[str] = None

    def add_output_field(
        self, ftype: str, name: str, casts: FrozenSet[Cast]
    ) -> "Token":
        self.output_fields.append(TokenOutputField(ftype, name, casts))
        return self

    def add_output_fields(self, fields: Sequence[TokenOutputField]) -> "Token":
        self.output_fields.extend(fields)
        return self

    def can_produce_a_desired_field_name(self, desired: Set[str]) -> bool:
        return any(f.name in desired for f in self.output_fields)

    def token_was_used(self) -> None:
        if self.warning_message_when_used:
            import logging

            from ..observability import log_warning_once

            # slf4j-style: any remaining {} placeholder takes the output
            # fields (the field-name one was filled at token-match time).
            message = self.warning_message_when_used.replace(
                "{}", str(self.output_fields), 1
            )
            # Once per process, not once per format assembly: every parser
            # build (oracle + metadata + per-worker instances) re-emits
            # identical token warnings — e.g. "Only some parts of localized
            # timestamps are supported" spamming the bench/multichip tails.
            # Repeats are counted (observability.suppressed_warning_counts).
            log_warning_once(logging.getLogger(__name__), message)

    def __repr__(self) -> str:
        return f"{{{self.output_fields} ({self.start_pos}+{self.length});Prio={self.prio}}}"


class FixedStringToken(Token):
    """A literal separator between value tokens."""


class TokenParser:
    """One format-token definition: literal token -> output spec + value regex."""

    def __init__(
        self,
        log_format_token: str,
        value_name: Optional[str] = None,
        value_type: Optional[str] = None,
        casts: Optional[FrozenSet[Cast]] = None,
        regex: str = "",
        prio: Optional[int] = None,
        custom_dissector: Optional[Dissector] = None,
    ):
        self.log_format_token = log_format_token
        self.regex = regex
        # Java ctor defaults: the value-carrying ctor defaults prio=10, the
        # regex-only ctor defaults prio=0 (TokenParser.java:80-128).
        if prio is None:
            prio = 10 if value_name is not None else 0
        self.prio = prio
        self.custom_dissector = custom_dissector
        self.warning_message_when_used: Optional[str] = None
        self.output_fields: List[TokenOutputField] = []
        if value_name is not None:
            self.add_output_field(value_type, value_name, casts)

    def add_output_field(
        self, ftype: str, name: str, casts: FrozenSet[Cast], deprecate_for: Optional[str] = None
    ) -> "TokenParser":
        f = TokenOutputField(ftype, name, casts)
        if deprecate_for:
            f.deprecate_for(deprecate_for)
        self.output_fields.append(f)
        return self

    def set_warning_message_when_used(self, message: str) -> "TokenParser":
        self.warning_message_when_used = message
        return self

    def get_next_token(self, log_format: str, start_offset: int) -> Optional[Token]:
        pos = log_format.find(self.log_format_token, start_offset)
        if pos == -1:
            return None
        token = Token(self.regex, pos, len(self.log_format_token), self.prio)
        token.add_output_fields(self.output_fields)
        if self.warning_message_when_used:
            token.warning_message_when_used = self.warning_message_when_used
        if not self._add_custom_dissector(
            token, self.output_fields[0].type, self.output_fields[0].name
        ):
            return None
        return token

    def get_tokens(self, log_format: str) -> Optional[List[Token]]:
        if not log_format or not log_format.strip():
            return None
        result: List[Token] = []
        offset = 0
        while True:
            token = self.get_next_token(log_format, offset)
            if token is None:
                break
            result.append(token)
            offset = token.start_pos + token.length
        return result

    def _add_custom_dissector(
        self, token: Token, field_type: str, field_name: str
    ) -> bool:
        if self.custom_dissector is None:
            return True
        try:
            dissector = self.custom_dissector.get_new_instance()
            dissector.set_input_type(field_type)
            if not dissector.initialize_from_settings_parameter(field_name):
                return False
            token.custom_dissector = dissector
        except Exception:  # noqa: BLE001 — any failure invalidates the token
            return False
        return True


class NotImplementedTokenParser(TokenParser):
    """Placeholder for known-but-unsupported variables: output name is
    ``<prefix>_<token mangled>`` of type NOT_IMPLEMENTED
    (TokenFormatDissector.java:89-103)."""

    def __init__(
        self,
        log_format_token: str,
        field_prefix: str,
        regex: str = ".*",
        prio: int = 0,
    ):
        name = field_prefix + "_" + re.sub(
            "[^a-z0-9_]", "_", log_format_token.lower()
        )
        super().__init__(
            log_format_token, name, "NOT_IMPLEMENTED", STRING_ONLY, regex, prio
        )


class FixedStringTokenParser(TokenParser):
    """E.g. ``%%`` -> literal ``%`` (FixedStringTokenParser in the reference)."""

    def __init__(self, log_format_token: str, literal: str):
        super().__init__(log_format_token, regex=literal, prio=0)
        self.literal = literal

    def get_next_token(self, log_format: str, start_offset: int) -> Optional[Token]:
        pos = log_format.find(self.log_format_token, start_offset)
        if pos == -1:
            return None
        return FixedStringToken(
            self.literal, pos, len(self.log_format_token), self.prio
        )


class NamedTokenParser(TokenParser):
    """Token pattern capturing the field name (e.g. ``%{referer}i``)."""

    def __init__(
        self,
        log_format_token_pattern: str,
        value_name_prefix: str,
        value_type: str,
        casts: FrozenSet[Cast],
        regex: str,
        prio: int = 0,
    ):
        super().__init__(
            log_format_token_pattern, value_name_prefix, value_type, casts, regex
        )
        self.prio = prio
        self.pattern = re.compile(log_format_token_pattern)

    def get_next_token(self, log_format: str, start_offset: int) -> Optional[Token]:
        m = self.pattern.search(log_format[start_offset:])
        if m is None:
            return None
        field_name = m.group(1) if m.re.groups > 0 else ""
        token = Token(
            self.regex, start_offset + m.start(), m.end() - m.start(), self.prio
        )
        for f in self.output_fields:
            token.add_output_field(f.type, f.name + field_name, f.casts)
        if self.warning_message_when_used:
            token.warning_message_when_used = self.warning_message_when_used.replace(
                "{}", field_name, 1
            )
        return token


class ParameterizedTokenParser(TokenParser):
    """Token whose ``{parameter}`` configures a custom dissector; the output
    TYPE embeds an MD5 of the parameter so each distinct parameter gets its own
    dissector instance (ParameterizedTokenParser.java:115-132)."""

    def __init__(
        self,
        log_format_token_pattern: str,
        value_name: str,
        value_type: str,
        casts: FrozenSet[Cast],
        regex: str,
        prio: int,
        custom_dissector: Dissector,
    ):
        super().__init__(
            log_format_token_pattern,
            value_name,
            value_type,
            casts,
            regex,
            custom_dissector=custom_dissector,
        )
        self.prio = prio
        self.pattern = re.compile(log_format_token_pattern)

    def token_parameter_to_type_name(self, parameter: str) -> str:
        md5 = hashlib.md5(parameter.encode("utf-8")).hexdigest()
        cleaned = re.sub("[^A-Za-z0-9]", "", parameter)
        return (self.output_fields[0].type + cleaned + "_" + md5).upper()

    def get_next_token(self, log_format: str, start_offset: int) -> Optional[Token]:
        m = self.pattern.search(log_format[start_offset:])
        if m is None:
            return None
        parameter = m.group(1) if m.re.groups > 0 else ""
        token = Token(
            self.regex, start_offset + m.start(), m.end() - m.start(), self.prio
        )
        field_type = self.token_parameter_to_type_name(parameter)
        for f in self.output_fields:
            token.add_output_field(field_type, f.name, f.casts)
            self._add_custom_dissector_param(token, field_type, parameter)
        if self.warning_message_when_used:
            token.warning_message_when_used = self.warning_message_when_used.replace(
                "{}", parameter, 1
            )
        return token

    def _add_custom_dissector_param(
        self, token: Token, field_type: str, parameter: str
    ) -> bool:
        if self.custom_dissector is None:
            return True
        try:
            dissector = self.custom_dissector.get_new_instance()
            dissector.set_input_type(field_type)
            if not dissector.initialize_from_settings_parameter(parameter):
                return False
            token.custom_dissector = dissector
        except Exception:  # noqa: BLE001
            return False
        return True


def _token_sort_key(token: Token) -> int:
    return token.start_pos


class TokenFormatDissector(Dissector):
    """Abstract format->regex compiler + per-line executor (the oracle path).

    Subclasses provide the token-parser table (``create_all_token_parsers``),
    optional format cleanup, and per-value decoding.
    """

    def __init__(self, log_format: Optional[str] = None):
        self.log_format: Optional[str] = None
        self.log_format_tokens: List[Token] = []
        self.output_types: List[str] = []
        self.requested_fields: Set[str] = set()
        self._input_type: Optional[str] = None
        self._pattern: Optional[re.Pattern] = None
        self._used_tokens: List[Token] = []
        self._regex: Optional[str] = None
        self._usable = False
        if log_format is not None:
            self.set_log_format(log_format)

    # -- abstract hooks -------------------------------------------------

    def create_all_token_parsers(self) -> List[TokenParser]:
        raise NotImplementedError

    def decode_extracted_value(self, token_name: str, value: str) -> Optional[str]:
        """Clean/decode/interpret a raw extracted value."""
        return value

    def cleanup_log_format(self, token_log_format: str) -> str:
        return token_log_format

    # -- configuration --------------------------------------------------

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.set_log_format(settings)
        return True

    def initialize_new_instance(self, new_instance: "Dissector") -> None:
        if isinstance(new_instance, TokenFormatDissector) and self.log_format:
            new_instance.set_log_format(self.log_format)

    def set_log_format(self, log_format: str) -> None:
        self.log_format = log_format
        self.log_format_tokens = self._parse_token_log_file_definition(log_format)
        self.output_types = []
        for token in self.log_format_tokens:
            if isinstance(token, FixedStringToken):
                continue
            for f in token.output_fields:
                self.output_types.append(f.type + ":" + f.name)

    def get_log_format(self) -> Optional[str]:
        return self.log_format

    def get_log_format_regex(self) -> Optional[str]:
        return self._regex

    # -- Dissector SPI ---------------------------------------------------

    def set_input_type(self, new_input_type: str) -> None:
        self._input_type = new_input_type

    def get_input_type(self) -> str:
        return self._input_type

    def get_possible_output(self) -> List[str]:
        return list(self.output_types)

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        self.requested_fields.add(output_name)
        for token in self.log_format_tokens:
            for f in token.output_fields:
                if output_name == f.name:
                    f.was_used()
                    return f.casts
        return STRING_ONLY

    def prepare_for_run(self) -> None:
        """Assemble THE anchored regex: capture groups only for demanded tokens
        (TokenFormatDissector.java:179-213)."""
        parts = ["^"]
        self._used_tokens = []
        for token in self.log_format_tokens:
            token.token_was_used()
            if isinstance(token, FixedStringToken):
                parts.append(re.escape(token.regex))
            elif token.can_produce_a_desired_field_name(self.requested_fields):
                self._used_tokens.append(token)
                parts.append("(" + token.regex + ")")
            else:
                parts.append("(?:" + token.regex + ")")
        parts.append("$")
        self._regex = "".join(parts)
        self._pattern = re.compile(self._regex)
        self._usable = True

    def create_additional_dissectors(self, parser: "Parser") -> None:
        for token in self.log_format_tokens:
            if token.custom_dissector is not None:
                parser.add_dissector(token.custom_dissector)

    def dissect(self, parsable: "Parsable", input_name: str) -> None:
        if not self._usable:
            raise DissectionFailure("Dissector in unusable state")
        line_field = parsable.get_parsable_field(self._input_type, input_name)
        line = line_field.value.get_string()

        m = self._pattern.search(line) if line is not None else None
        if m is None:
            raise DissectionFailure(
                "The input line does not match the specified log format."
                f"Line     : {line}\n"
                f"LogFormat: {self.log_format}\n"
                f"RegEx    : {self._regex}"
            )
        for i, token in enumerate(self._used_tokens, start=1):
            matched = m.group(i)
            for f in token.output_fields:
                parsable.add_dissection(
                    input_name,
                    f.type,
                    f.name,
                    self.decode_extracted_value(f.name, matched),
                )

    # -- format compilation ---------------------------------------------

    def _parse_token_log_file_definition(self, token_log_format: str) -> List[Token]:
        """Scan the format with every token parser, resolve overlaps by
        priority/length, fill the gaps with fixed-string separators
        (TokenFormatDissector.java:294-379)."""
        token_parsers = self.create_all_token_parsers()
        tokens: List[Token] = []
        cleaned = self.cleanup_log_format(token_log_format)

        for tp in token_parsers:
            new_tokens = tp.get_tokens(cleaned)
            if new_tokens:
                tokens.extend(new_tokens)

        tokens.sort(key=_token_sort_key)

        # Kick duplicates with lower prio / shorter length, and overlaps.
        kicked: List[Token] = []
        prev: Optional[Token] = None
        for token in tokens:
            if prev is None:
                prev = token
                continue
            if prev.start_pos == token.start_pos:
                if prev.length == token.length:
                    if prev.prio < token.prio:
                        kicked.append(prev)
                    else:
                        kicked.append(token)
                        continue
                elif prev.length < token.length:
                    kicked.append(prev)
                else:
                    kicked.append(token)
                    continue
            elif prev.start_pos + prev.length > token.start_pos:
                # Partial overlap (e.g. %{%H}t also matching %H): kick the later.
                kicked.append(token)
                continue
            prev = token

        kicked_ids = {id(t) for t in kicked}
        tokens = [t for t in tokens if id(t) not in kicked_ids]

        # Fill the holes with fixed-string separators.
        all_tokens: List[Token] = []
        token_end = 0
        for token in tokens:
            token_begin = token.start_pos
            if token_begin - token_end > 0:
                separator = cleaned[token_end:token_begin]
                all_tokens.append(
                    FixedStringToken(separator, token_begin, token_begin - token_end, 0)
                )
            all_tokens.append(token)
            token_end = token_begin + token.length
        if token_end < len(cleaned):
            separator = cleaned[token_end:]
            all_tokens.append(
                FixedStringToken(separator, token_end, len(cleaned) - token_end, 0)
            )
        return all_tokens

    # -- pickling: compiled patterns regenerate on demand ----------------

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_pattern"] = None
        state["_usable"] = False
        state["_used_tokens"] = []
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
