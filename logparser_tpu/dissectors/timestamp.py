"""TIME.STAMP dissector: one timestamp string -> 30 demand-driven outputs.

Rebuild of httpdlog/httpdlog-parser/.../dissectors/TimeStampDissector.java:
outputs day/month/monthname/week/year/hour/minute/second/ms/us/ns/date/time in
local + ``_utc`` variants, plus timezone + epoch millis (getPossibleOutput
:136-177); demand flags set in prepare_for_dissect (:222-352); default Apache
pattern ``dd/MMM/yyyy:HH:mm:ss ZZ`` (:46); ISO week fields (Locale.UK, :52).

Faithfully replicated quirk: getPossibleOutput declares ``TIME.ZONE:timezone``
but dissect emits type ``TIME.TIMEZONE`` — so a requested timezone field is
never actually delivered (the reference's own tests assert its absence,
TestTimeStampDissector.java:258).
"""
from __future__ import annotations

from typing import FrozenSet, List, Optional

from ..core.casts import Cast, NO_CASTS, STRING_ONLY, STRING_OR_LONG
from ..core.dissector import Dissector, extract_field_name
from ..core.exceptions import DissectionFailure
from ..core.fields import ParsedField
from .timelayout import (
    LocaleData,
    TimeLayout,
    TimestampParseError,
    compile_java_pattern,
    get_locale,
    week_based_fields,
)

DEFAULT_APACHE_DATE_TIME_PATTERN = "dd/MMM/yyyy:HH:mm:ss ZZ"

_LOCAL_FIELDS = [
    ("day", "TIME.DAY", STRING_OR_LONG),
    ("monthname", "TIME.MONTHNAME", STRING_ONLY),
    ("month", "TIME.MONTH", STRING_OR_LONG),
    ("weekofweekyear", "TIME.WEEK", STRING_OR_LONG),
    ("weekyear", "TIME.YEAR", STRING_OR_LONG),
    ("year", "TIME.YEAR", STRING_OR_LONG),
    ("hour", "TIME.HOUR", STRING_OR_LONG),
    ("minute", "TIME.MINUTE", STRING_OR_LONG),
    ("second", "TIME.SECOND", STRING_OR_LONG),
    ("millisecond", "TIME.MILLISECOND", STRING_OR_LONG),
    ("microsecond", "TIME.MICROSECOND", STRING_OR_LONG),
    ("nanosecond", "TIME.NANOSECOND", STRING_OR_LONG),
    ("date", "TIME.DATE", STRING_ONLY),
    ("time", "TIME.TIME", STRING_ONLY),
]


class TimeStampDissector(Dissector):
    def __init__(
        self,
        date_time_pattern: str = DEFAULT_APACHE_DATE_TIME_PATTERN,
        input_type: str = "TIME.STAMP",
        locale: Optional[str] = None,
    ):
        self._input_type = input_type
        if not date_time_pattern or not date_time_pattern.strip():
            date_time_pattern = DEFAULT_APACHE_DATE_TIME_PATTERN
        self.date_time_pattern = date_time_pattern
        # Reference default is Locale.UK — English names, ISO week fields
        # (TimeStampDissector.java:52).
        self.locale = get_locale(locale)
        self._layout: Optional[TimeLayout] = None
        self.wanted: set = set()

    # -- configuration ---------------------------------------------------

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.set_date_time_pattern(settings)
        return True

    def set_date_time_pattern(self, pattern: str) -> None:
        self.date_time_pattern = pattern
        self._layout = None

    def set_locale(self, locale) -> "TimeStampDissector":
        """Month/weekday name tables + week rule for parsing and the
        monthname/week outputs (TimeStampDissector.java:73-78 setLocale).
        Accepts a tag ("fr", "en_US") or a LocaleData; returns self like
        the reference's builder-style setter."""
        self.locale = (
            locale if isinstance(locale, LocaleData) else get_locale(locale)
        )
        if self._layout is not None:
            self._layout = self._layout.with_locale(self.locale)
        return self

    def set_layout(self, layout: TimeLayout) -> None:
        """Install a pre-compiled layout (used by the strftime front-end)."""
        self._layout = layout.with_locale(self.locale)

    def get_layout(self) -> TimeLayout:
        if self._layout is None:
            self._layout = compile_java_pattern(
                self.date_time_pattern, locale=self.locale
            )
        return self._layout

    def get_new_instance(self) -> "Dissector":
        new = type(self)()
        self.initialize_new_instance(new)
        return new

    def initialize_new_instance(self, new_instance: "Dissector") -> None:
        new_instance._input_type = self._input_type
        new_instance.date_time_pattern = self.date_time_pattern
        new_instance.locale = self.locale
        if self._layout is not None:
            new_instance._layout = self._layout

    # -- SPI -------------------------------------------------------------

    def get_input_type(self) -> str:
        return self._input_type

    def set_input_type(self, new_input_type: str) -> None:
        self._input_type = new_input_type

    def get_possible_output(self) -> List[str]:
        result = []
        for name, ftype, _ in _LOCAL_FIELDS:
            result.append(f"{ftype}:{name}")
        result.append("TIME.ZONE:timezone")
        result.append("TIME.EPOCH:epoch")
        for name, ftype, _ in _LOCAL_FIELDS:
            result.append(f"{ftype}:{name}_utc")
        return result

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        base = name[:-4] if name.endswith("_utc") else name
        for fname, _, casts in _LOCAL_FIELDS:
            if fname == base:
                self.wanted.add(name)
                return casts
        if name == "timezone":
            self.wanted.add(name)
            return STRING_ONLY
        if name == "epoch":
            self.wanted.add(name)
            return STRING_OR_LONG
        return NO_CASTS

    # -- dissection ------------------------------------------------------

    def dissect(self, parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self._input_type, input_name)
        self.dissect_field(parsable, input_name, field)

    def dissect_field(self, parsable, input_name: str, field: ParsedField) -> None:
        value = field.value.get_string()
        if value is None or value == "":
            return

        try:
            ts = self.get_layout().parse(value)
        except TimestampParseError as e:
            raise DissectionFailure(str(e)) from e
        except (ValueError, IndexError) as e:
            raise DissectionFailure(f"Unable to parse timestamp {value!r}: {e}") from e

        w = self.wanted
        if "timezone" in w:
            parsable.add_dissection(
                input_name, "TIME.TIMEZONE", "timezone", ts.zone_display_name()
            )
        if "epoch" in w:
            parsable.add_dissection(input_name, "TIME.EPOCH", "epoch", ts.epoch_millis)

        self._emit_components(parsable, input_name, ts, suffix="")
        if any(name.endswith("_utc") for name in w):
            self._emit_components(parsable, input_name, ts.utc_fields(), suffix="_utc")

    def _emit_components(self, parsable, input_name, ts, suffix: str) -> None:
        w = self.wanted
        add = parsable.add_dissection
        if "day" + suffix in w:
            add(input_name, "TIME.DAY", "day" + suffix, ts.day)
        if "monthname" + suffix in w:
            # getDisplayName(TextStyle.FULL, locale): the locale's full
            # month name for BOTH local and _utc (TimeStampDissector.java
            # :446-447, :510-511).
            add(input_name, "TIME.MONTHNAME", "monthname" + suffix,
                self.locale.months_full[ts.month - 1])
        if "month" + suffix in w:
            add(input_name, "TIME.MONTH", "month" + suffix, ts.month)
        if "weekofweekyear" + suffix in w:
            # Local weeks follow WeekFields.of(locale) (:455-459); the
            # _utc twins stay WeekFields.ISO (:519-523).
            wk = (
                ts.iso_week() if suffix
                else week_based_fields(
                    ts.year, ts.month, ts.day,
                    self.locale.week_first_day, self.locale.week_min_days,
                )[1]
            )
            add(input_name, "TIME.WEEK", "weekofweekyear" + suffix, wk)
        if "weekyear" + suffix in w:
            wy = (
                ts.iso_weekyear() if suffix
                else week_based_fields(
                    ts.year, ts.month, ts.day,
                    self.locale.week_first_day, self.locale.week_min_days,
                )[0]
            )
            add(input_name, "TIME.YEAR", "weekyear" + suffix, wy)
        if "year" + suffix in w:
            add(input_name, "TIME.YEAR", "year" + suffix, ts.year)
        if "hour" + suffix in w:
            add(input_name, "TIME.HOUR", "hour" + suffix, ts.hour)
        if "minute" + suffix in w:
            add(input_name, "TIME.MINUTE", "minute" + suffix, ts.minute)
        if "second" + suffix in w:
            add(input_name, "TIME.SECOND", "second" + suffix, ts.second)
        if "millisecond" + suffix in w:
            add(input_name, "TIME.MILLISECOND", "millisecond" + suffix,
                ts.nano // 1_000_000)
        if "microsecond" + suffix in w:
            add(input_name, "TIME.MICROSECOND", "microsecond" + suffix,
                ts.nano // 1_000)
        if "nanosecond" + suffix in w:
            add(input_name, "TIME.NANOSECOND", "nanosecond" + suffix, ts.nano)
        if "date" + suffix in w:
            add(input_name, "TIME.DATE", "date" + suffix, ts.date_str())
        if "time" + suffix in w:
            add(input_name, "TIME.TIME", "time" + suffix, ts.time_str())
