"""Distributed execution: device meshes, data-parallel and sequence-parallel
split-program execution.

The reference's "distributed layer" is embarrassingly-parallel data parallelism
over line batches: the host engine splits the file and ships a serialized
parser config to independent workers (SURVEY §2.4/§5.8).  The TPU-native
equivalent:

- **DP**: shard the batch dimension of the ``[B, L]`` buffer over a
  ``jax.sharding.Mesh`` axis; the split program has no cross-line dependency,
  so XLA partitions it with zero collectives in the hot loop.  Counter
  aggregation (good/bad lines) is the only cross-device reduction.
- **SP (long lines)**: the analogous axis to "long context" is line length
  (SURVEY §5.7).  ``run_program_sp`` shards L over a ``seq`` mesh axis inside
  ``shard_map``: every find-literal op computes a local candidate position and
  resolves the global first occurrence with ``lax.pmin`` over the seq axis;
  multi-byte separators crossing shard boundaries are handled with a halo
  exchange via ``lax.ppermute``; charset validation aggregates violation
  counts with ``lax.psum``.  Collectives ride ICI; no host round-trips.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu.program import DeviceProgram
from ..tpu.runtime import _run_program_impl


def make_mesh(
    n_data: int, n_seq: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = n_data * n_seq
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(n_data, n_seq)
    return Mesh(dev_array, axis_names=("data", "seq"))


def dp_device_count(requested: Optional[int] = None) -> int:
    """The data-parallel width a parser mesh should use: the largest
    power of two <= min(requested, local device count).  Power-of-two
    widths always divide the power-of-two batch buckets the parser pads
    to, so the sharded batch axis never needs uneven-shard handling in
    the hot path; a leftover odd device idles rather than forcing a
    repad (document, don't surprise)."""
    avail = len(jax.devices())
    n = avail if requested is None else min(int(requested), avail)
    if n < 1:
        return 1
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def dp_shardings(mesh: Mesh):
    """The ONE definition of the fused parse step's data-parallel
    layout: inputs ``(buf [B, L], lengths [B])`` sharded over the
    ``data`` axis, packed output ``[K, B]`` sharded on its batch
    column axis.  Shared by :func:`batch_parallel_runner` (the dryrun /
    test harness) and ``TpuBatchParser(data_parallel=...)`` (the
    product hot path) so the two can never drift."""
    return (
        (
            NamedSharding(mesh, P("data", None)),
            NamedSharding(mesh, P("data")),
        ),
        NamedSharding(mesh, P(None, "data")),
    )


# ---------------------------------------------------------------------------
# Data-parallel execution: shard B, replicate the program.
# ---------------------------------------------------------------------------

def data_parallel_runner(program: DeviceProgram, mesh: Mesh):
    """jitted fn(buf [B, L], lengths [B]) with batch sharded over 'data'."""
    in_shardings = (
        NamedSharding(mesh, P("data", None)),
        NamedSharding(mesh, P("data")),
    )
    fn = functools.partial(_run_program_impl, program)
    return jax.jit(fn, in_shardings=in_shardings)


def batch_parallel_runner(units, mesh: Mesh, view_specs=None):
    """The FULL fused field-extraction step under data parallelism:
    jitted fn(buf [B, L], lengths [B]) -> packed [K, B] int32 with the
    batch axis sharded over 'data'.

    Unlike :func:`data_parallel_runner` (split program only), this shards
    the complete per-parser pipeline — split + chained sub-dissector
    stages (firstline/URI splits, timestamps, CSR wildcards, GeoIP joins)
    — exactly what ``TpuBatchParser`` executes per batch.  The per-line
    computation has no cross-line dependency, so XLA partitions it with
    zero collectives in the hot loop.  ``view_specs`` (round 5) appends
    the device-emitted Arrow view rows, sharded the same way — the
    parse_batch product path."""
    from ..tpu.pipeline import units_fn, units_views_fn

    # The same executor body TpuBatchParser jits.
    fn = units_views_fn(units, view_specs) if view_specs else units_fn(units)

    in_shardings, out_shardings = dp_shardings(mesh)
    return jax.jit(fn, in_shardings=in_shardings, out_shardings=out_shardings)


# ---------------------------------------------------------------------------
# Sequence-parallel execution: shard L over 'seq' inside shard_map.
# ---------------------------------------------------------------------------

def _sp_find_literal(buf_local, lengths, lit, cursor, offset, l_total, axis):
    """Global first occurrence >= cursor of `lit`, with halo for multi-byte
    literals; returns l_total when absent."""
    B, Lc = buf_local.shape
    n_lit = len(lit)

    if n_lit > 1:
        n_shards = lax.psum(1, axis)
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        halo = lax.ppermute(buf_local[:, : n_lit - 1], axis, perm)
        ext = jnp.concatenate([buf_local, halo], axis=1)
    else:
        ext = buf_local

    match = jnp.ones((B, Lc), dtype=bool)
    for k, byte in enumerate(lit):
        match = match & (ext[:, k : k + Lc] == np.uint8(byte))

    local_pos = jnp.arange(Lc, dtype=jnp.int32)
    global_pos = local_pos[None, :] + offset
    usable = (
        match
        & (global_pos + n_lit <= lengths[:, None])
        & (global_pos >= cursor[:, None])
    )
    cand = jnp.where(usable, global_pos, l_total)
    local_min = jnp.min(cand, axis=1)
    return lax.pmin(local_min, axis)


def _sp_byte_at(buf_local, idx, offset, axis):
    """buf[global idx] with each global position owned by one shard."""
    Lc = buf_local.shape[1]
    local = idx - offset
    in_range = (local >= 0) & (local < Lc)
    safe = jnp.clip(local, 0, Lc - 1)
    b = jnp.take_along_axis(buf_local, safe[:, None], axis=1)[:, 0]
    contrib = jnp.where(in_range, b.astype(jnp.int32), 0)
    return lax.psum(contrib, axis)


def _sp_charset_ok(buf_local, start, end, cs_table_row, offset, axis):
    Lc = buf_local.shape[1]
    local_pos = jnp.arange(Lc, dtype=jnp.int32)
    global_pos = local_pos[None, :] + offset
    in_span = (global_pos >= start[:, None]) & (global_pos < end[:, None])
    bad = in_span & ~cs_table_row[buf_local]
    local_bad = jnp.sum(bad.astype(jnp.int32), axis=1)
    return lax.psum(local_bad, axis) == 0


def _sp_program_body(program: DeviceProgram, l_total: int, axis: str,
                     buf_local, lengths):
    B, Lc = buf_local.shape
    offset = lax.axis_index(axis).astype(jnp.int32) * Lc

    cursor = jnp.zeros(B, dtype=jnp.int32)
    valid = jnp.ones(B, dtype=bool)
    n_tok = len(program.tokens)
    starts = jnp.zeros((n_tok, B), dtype=jnp.int32)
    ends = jnp.zeros((n_tok, B), dtype=jnp.int32)
    charset_table = jnp.asarray(program.charset_table)

    for op in program.ops:
        if op.kind == "lit":
            ok = jnp.ones(B, dtype=bool)
            for k, byte in enumerate(op.lit):
                b = _sp_byte_at(buf_local, cursor + k, offset, axis)
                ok = ok & (b == byte)
            ok = ok & (cursor + len(op.lit) <= lengths)
            valid = valid & ok
            cursor = cursor + len(op.lit)
        elif op.kind in ("until_lit", "to_end"):
            if op.kind == "until_lit":
                found = _sp_find_literal(
                    buf_local, lengths, op.lit, cursor, offset, l_total, axis
                )
                token_valid = found < l_total
                start, end = cursor, jnp.where(token_valid, found, cursor)
                valid = valid & token_valid
                next_cursor = end + len(op.lit)
            else:
                start, end = cursor, lengths
                next_cursor = end
            cs_row = charset_table[program.charset_ids[op.charset]]
            valid = (
                valid
                & _sp_charset_ok(buf_local, start, end, cs_row, offset, axis)
                & ((end - start) >= op.min_len)
            )
            if op.max_len:
                valid = valid & ((end - start) <= op.max_len)
            starts = starts.at[op.token_index].set(start)
            ends = ends.at[op.token_index].set(end)
            cursor = next_cursor
        else:  # pragma: no cover
            raise AssertionError(op.kind)

    valid = valid & (cursor == lengths)
    return {"starts": starts, "ends": ends, "valid": valid}


def sequence_parallel_runner(program: DeviceProgram, mesh: Mesh, l_total: int):
    """jitted fn(buf [B, L], lengths [B]) with B sharded over 'data' and L
    sharded over 'seq'; per-op global resolution via pmin/psum collectives."""
    try:
        from jax import shard_map  # jax >= 0.6 public export
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    body = functools.partial(_sp_program_body, program, l_total, "seq")
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P("data", "seq"), P("data")),
        out_specs={"starts": P(None, "data"), "ends": P(None, "data"),
                   "valid": P("data")},
    )
    return jax.jit(mapped)


def aggregate_counters(mesh: Mesh, good: jnp.ndarray, bad: jnp.ndarray):
    """Global good/bad line counters: the only cross-device reduction of the
    DP hot loop (the reference's Hadoop counters, RecordReader.java:118-120)."""

    def reduce_fn(g, b):
        return jnp.sum(g), jnp.sum(b)

    return jax.jit(reduce_fn)(good, bad)
