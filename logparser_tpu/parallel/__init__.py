"""Device-mesh parallel execution (DP over batch, SP over line length)."""
from .mesh import (
    aggregate_counters,
    data_parallel_runner,
    make_mesh,
    sequence_parallel_runner,
)

__all__ = [
    "make_mesh",
    "data_parallel_runner",
    "sequence_parallel_runner",
    "aggregate_counters",
]
