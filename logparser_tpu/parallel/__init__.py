"""Device-mesh parallel execution (DP over batch, SP over line length)."""
from .mesh import (
    aggregate_counters,
    batch_parallel_runner,
    data_parallel_runner,
    dp_device_count,
    dp_shardings,
    make_mesh,
    sequence_parallel_runner,
)

__all__ = [
    "make_mesh",
    "batch_parallel_runner",
    "data_parallel_runner",
    "dp_device_count",
    "dp_shardings",
    "sequence_parallel_runner",
    "aggregate_counters",
]
