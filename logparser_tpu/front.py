"""Replicated front tier: one acceptor over a supervised sidecar fleet.

ROADMAP open item 2 ("Planet-facing serving"): round 14 made ONE process
serve many sessions well; this module multiplies processes.  A
:class:`FrontTier` is a TCP acceptor speaking the existing wire protocol
(docs/PROTOCOL.md — nothing changes for clients) that fans sessions out
to N supervised SIDECAR worker processes (each a
``python -m logparser_tpu.service --sidecar``, each owning its own
device/core budget), with:

- **Per-format affinity routing.**  A session is routed by its parser
  cache key (:meth:`~logparser_tpu.service._ParserCache.key_of` of its
  CONFIG) via rendezvous (highest-random-weight) hashing, so the same
  format lands on the same sidecar and that sidecar's compiled-parser
  cache, jit shape buckets, and coalescing lanes stay HOT — CelerLog's
  route-by-format dispatching and LogLSHD's bucket-by-signature idea
  (PAPERS.md) applied at fleet scale.  When the first choice's live
  coalesce-queue occupancy (scraped from its ``/metrics``) crosses
  ``spill_occupancy``, the session SPILLS to its second rendezvous
  choice (``front_spills_total``) — a hot format widens to two warm
  sidecars instead of melting one.
- **Supervision** (the serving twin of ``feeder/supervisor.py``, one
  level up): every sidecar is health-checked (``/readyz`` probe + a
  heartbeat deadline over its ``/metrics`` scrape); a crashed sidecar
  is respawned with a bounded restart budget and exponential backoff,
  a WEDGED one (alive but silent past ``heartbeat_deadline_s``) is
  killed first, and a FLAPPING one trips a circuit breaker
  (open -> half-open trial -> closed) so routing steers around it while
  it recovers.  The pure decision machine is :class:`FrontSupervisor` —
  no sockets, no sleeps; tests drive it directly.
- **Crash failover, never a reset.**  A session proxied to a sidecar
  that dies mid-flight is answered with a structured
  ``BUSY {"reason":"sidecar_failover"}`` frame (counted
  ``front_failovers_total``) and closed cleanly: a retrying client
  (``ParseServiceClient`` reconnects on that reason) lands on a live
  sidecar after one warmup.  Affinity is what makes this cheap — any
  sidecar can absorb a key after one compile.
- **Per-tenant fairness** on the front admission tier: a CONFIG may
  carry a ``tenant`` key; quotas bound one tenant's concurrent sessions
  (``tenant_max_sessions``) and in-flight lines
  (``tenant_max_inflight_lines``), shedding
  ``BUSY {"reason":"tenant_quota"}`` (``front_tenant_shed_total``)
  so one noisy tenant cannot starve the fleet.
- **Zero-downtime rolling restart.**  :meth:`FrontTier.roll` drains one
  sidecar at a time under the round-12 drain machinery (SIGTERM ->
  ``/readyz`` flip -> admitted sessions finish) while routing sends its
  keys to the rest, then respawns it and moves on — the config/version
  swap story with the listener never blinking.
- **Fleet observability.**  The front's HTTP endpoint merges every
  sidecar's ``/metrics`` exposition under a ``sidecar`` label alongside
  the front's own families (``front_sessions_routed_total{key,sidecar}``,
  ``front_failovers_total``, ``front_tenant_shed_total{tenant}``, ...),
  and registers fleet-wide sidecar occupancy as a process backpressure
  source (:func:`logparser_tpu.feeder.register_backpressure_source`) —
  the cross-process aggregation of the per-process signal the admission
  tier already sheds on.

Drilled by ``make fleet-smoke`` (``tools/fleet_smoke.py``: a 1-of-3
hard kill and a live rolling restart under loadgen traffic) and gated
in ``bench.py``'s ``fleet`` section (goodput scaling 1->N, kill-drill
retention); chaos primitives ``kill_sidecar``/``wedge_sidecar``/
``flap_sidecar`` (``tools/chaos.py``) produce the failures on purpose.
docs/SERVICE.md "Fleet" is the ops runbook.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import re
import signal
import socket
import socketserver
import struct
import subprocess
import sys
import threading
import time
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .observability import log_warning_once, metrics, note_teardown
from .service import (
    _ERROR_MARKER,
    _MAX_FRAME,
    RECONNECT_BUSY_REASONS,
    _FrameTooLarge,
    _ParserCache,
    _SessionTimeout,
    _linger_drain,
    _recv_exact_timed,
    busy_error_text,
    write_error,
    write_frame,
)
from .tracing import (
    flight_event,
    flightz_payload,
    root_span,
    tracez_payload,
)

LOG = logging.getLogger(__name__)

#: Bound on distinct client-controlled metric label values (parser-key
#: labels, tenant names) before the tail aggregates as ``overflow`` —
#: the registry keeps every series forever, so unbounded label spaces
#: are a memory leak an unauthenticated peer could drive.
_MAX_METRIC_LABELS = 256


# ---------------------------------------------------------------------------
# policy + the pure supervision machine
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FrontPolicy:
    """Tunables of the front tier (docs/SERVICE.md "Fleet").  Defaults
    favor fast recovery and fast tests; production deployments mostly
    raise the budgets."""

    #: Faults per sidecar inside ``restart_budget_window_s`` before the
    #: slot is DISABLED (stops being respawned; routing skips it until
    #: the next :meth:`FrontTier.roll` revives it deliberately).
    max_restarts: int = 5
    restart_budget_window_s: float = 60.0
    #: Exponential backoff before respawn k of a window: base * 2**(k-1).
    backoff_base_s: float = 0.25
    backoff_max_s: float = 5.0
    #: Health probe period and the silence budget after which an ALIVE
    #: but unresponsive sidecar is declared wedged and killed.
    heartbeat_interval_s: float = 0.5
    heartbeat_deadline_s: float = 5.0
    #: Circuit breaker: ``circuit_threshold`` faults inside
    #: ``flap_window_s`` open the circuit for ``circuit_open_s`` (routing
    #: steers around the sidecar), then ONE half-open trial session
    #: probes it — success closes the circuit, a fault re-opens it.
    circuit_threshold: int = 3
    flap_window_s: float = 10.0
    circuit_open_s: float = 5.0
    #: First-choice coalesce-queue occupancy (0-1 fraction of the
    #: sidecar's bounded submission queue, scraped live from /metrics)
    #: at/above which a session spills to its second rendezvous choice.
    spill_occupancy: float = 0.5
    #: Per-tenant fairness quotas (0 = unlimited): concurrent sessions
    #: and in-flight lines per CONFIG ``tenant`` identity.
    tenant_max_sessions: int = 0
    tenant_max_inflight_lines: int = 0
    #: Front-wide admitted-session bound (the fleet's aggregate budget
    #: lives in the sidecars' own max_sessions; this one only stops a
    #: socket flood from exhausting front fds).
    max_sessions: int = 1024
    #: Fleet-wide occupancy fraction at/above which NEW sessions shed
    #: BUSY{"reason":"backpressure"} at the front door.
    backpressure_threshold: float = 0.95
    busy_retry_after_s: float = 0.25
    #: Socket windows (mirroring ServiceLimits semantics).
    connect_timeout_s: float = 2.0
    idle_timeout_s: Optional[float] = 600.0
    frame_timeout_s: Optional[float] = 30.0
    #: Upstream silence budget while a response is due: normally the
    #: prober kills a wedged sidecar long before this fires.
    upstream_timeout_s: Optional[float] = 300.0
    max_config_bytes: int = 1 << 20
    #: Sidecar spawn -> SIDECAR_READY budget (a cold jax import rides
    #: inside it) and the per-sidecar drain budget during a roll.
    ready_timeout_s: float = 120.0
    drain_timeout_s: float = 30.0


@dataclass
class FrontDecision:
    """What the fleet should do about one sidecar fault."""

    action: str                      # "respawn" | "disable"
    backoff_s: float = 0.0
    circuit_opened: bool = False


class FrontSupervisor:
    """Per-sidecar fault bookkeeping + circuit breaker — a PURE state
    machine (no processes, no sleeps, explicit ``now``), the fleet-level
    sibling of :class:`~logparser_tpu.feeder.supervisor.FeederSupervisor`.
    Circuit states per slot: ``closed`` (routable) -> ``open`` (faults >=
    ``circuit_threshold`` inside ``flap_window_s``; not routable) ->
    ``half_open`` (cool-off elapsed; exactly ONE trial session admitted)
    -> ``closed`` on trial success / ``open`` again on fault.  The
    restart budget is a sliding window: ``max_restarts`` faults inside
    ``restart_budget_window_s`` DISABLE the slot (quarantine, the
    route-around-the-data move one level up)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, policy: FrontPolicy, n: int):
        self.policy = policy
        self.n = n
        self.state = [self.CLOSED] * n
        self.opened_at = [0.0] * n
        self.fault_times: List[List[float]] = [[] for _ in range(n)]
        self.disabled = [False] * n
        self.total_restarts = 0          # respawns EXECUTED (fleet-counted)
        self.circuit_opens = [0] * n

    # -- faults ----------------------------------------------------------

    def on_fault(self, idx: int, now: float) -> FrontDecision:
        """One observed sidecar failure (death, wedge, connect refusal).
        Returns the respawn/disable decision; flips the circuit open at
        the flap threshold so routing steers around the slot while its
        respawns churn."""
        faults = self.fault_times[idx]
        faults.append(now)
        window = self.policy.restart_budget_window_s
        self.fault_times[idx] = faults = [
            t for t in faults if now - t <= window
        ]
        opened = False
        recent = [t for t in faults if now - t <= self.policy.flap_window_s]
        if (self.state[idx] != self.OPEN
                and len(recent) >= self.policy.circuit_threshold):
            self.state[idx] = self.OPEN
            self.opened_at[idx] = now
            self.circuit_opens[idx] += 1
            opened = True
        elif self.state[idx] == self.HALF_OPEN:
            # The trial failed: straight back to cooling.
            self.state[idx] = self.OPEN
            self.opened_at[idx] = now
        if len(faults) > self.policy.max_restarts:
            self.disabled[idx] = True
            return FrontDecision("disable", circuit_opened=opened)
        backoff = min(
            self.policy.backoff_max_s,
            self.policy.backoff_base_s * (2 ** (len(recent) - 1)),
        )
        return FrontDecision("respawn", backoff, opened)

    # -- routing signal --------------------------------------------------

    def routable(self, idx: int, now: float) -> bool:
        """Whether the router may hand ``idx`` a NEW session right now.
        An open circuit past its cool-off transitions to half-open and
        admits exactly this one call's session as the trial.  A
        half-open slot whose trial went STALE (admitted here but never
        actually routed — rendezvous order sent that session elsewhere,
        or its client vanished — and no success/fault ever reported
        inside another cool-off window) re-admits a fresh trial:
        without the escape a recovered sidecar could sit HALF_OPEN
        forever, silently shrinking the fleet."""
        if self.disabled[idx]:
            return False
        st = self.state[idx]
        if st == self.CLOSED:
            return True
        # OPEN past the cool-off, or HALF_OPEN with a stale trial:
        # admit (another) trial and restart the window clock.
        if now - self.opened_at[idx] >= self.policy.circuit_open_s:
            self.state[idx] = self.HALF_OPEN
            self.opened_at[idx] = now
            return True
        return False

    def on_success(self, idx: int, now: float) -> None:
        """A routed session reached its sidecar (CONFIG forwarded on a
        live connection).  A half-open trial success closes the circuit
        and clears the flap window."""
        if self.state[idx] == self.HALF_OPEN:
            self.state[idx] = self.CLOSED
            self.fault_times[idx] = []

    def on_deliberate_restart(self, idx: int) -> None:
        """A rolling restart replaced this sidecar ON PURPOSE: fresh
        slate — deliberate churn must not trip the breaker or eat the
        crash budget (and a roll revives a disabled slot)."""
        self.state[idx] = self.CLOSED
        self.fault_times[idx] = []
        self.disabled[idx] = False

    def summary(self) -> Dict[str, Any]:
        return {
            "restarts": self.total_restarts,
            "circuit_opens": list(self.circuit_opens),
            "disabled": [i for i in range(self.n) if self.disabled[i]],
            "states": list(self.state),
        }


# ---------------------------------------------------------------------------
# sidecar handles: one supervised worker process (or an in-process
# stand-in for tests/bench)
# ---------------------------------------------------------------------------


class SidecarSpawnError(RuntimeError):
    """A sidecar process failed to reach SIDECAR_READY."""


class ProcessSidecar:
    """One ``python -m logparser_tpu.service --sidecar`` child process.
    The constructor blocks until the child prints its SIDECAR_READY
    handshake (bound service + metrics ports) or dies/times out."""

    def __init__(self, index: int, *, host: str = "127.0.0.1",
                 extra_args: Sequence[str] = (),
                 ready_timeout_s: float = 120.0,
                 env: Optional[Dict[str, str]] = None):
        self.index = index
        cmd = [
            sys.executable, "-m", "logparser_tpu.service",
            "--sidecar", "--host", host, *extra_args,
        ]
        child_env = dict(os.environ)
        if env:
            child_env.update(env)
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=None, env=child_env,
            start_new_session=True, text=True,
        )
        ready: Dict[str, Any] = {}

        def read_ready() -> None:
            assert self._proc.stdout is not None
            for line in self._proc.stdout:
                if line.startswith("SIDECAR_READY "):
                    try:
                        ready.update(json.loads(line.split(" ", 1)[1]))
                    except ValueError:
                        pass
                    return

        reader = threading.Thread(target=read_ready, daemon=True)
        reader.start()
        reader.join(timeout=ready_timeout_s)
        if not ready:
            self.kill()
            raise SidecarSpawnError(
                f"sidecar {index} never reported SIDECAR_READY "
                f"(rc={self._proc.poll()})"
            )
        self.host = host
        self.port = int(ready["port"])
        self.metrics_port = int(ready["metrics_port"])
        # Keep the pipe drained so a chatty child can never block on a
        # full stdout buffer (logs ride stderr; this is belt-and-braces).
        threading.Thread(
            target=lambda: self._proc.stdout
            and self._proc.stdout.read(),
            daemon=True,
        ).start()

    @property
    def pid(self) -> int:
        return self._proc.pid

    def alive(self) -> bool:
        return self._proc.poll() is None

    def kill(self) -> None:
        """Hard death (SIGKILL): the crash-failover drill's primitive."""
        try:
            self._proc.kill()
        except OSError:
            pass

    def terminate(self) -> None:
        """SIGTERM: the sidecar CLI runs its graceful drain
        (docs/SERVICE.md) — readyz flips, admitted sessions finish."""
        try:
            self._proc.terminate()
        except OSError:
            pass

    def suspend(self, seconds: Optional[float] = None) -> None:
        """SIGSTOP — the WEDGE primitive: alive but silent, exactly what
        the heartbeat deadline exists to catch.  With ``seconds`` a
        timer SIGCONTs it back (the transient-stall shape)."""
        try:
            os.kill(self._proc.pid, signal.SIGSTOP)
        except OSError:
            return
        if seconds:
            def resume() -> None:
                try:
                    os.kill(self._proc.pid, signal.SIGCONT)
                except OSError:
                    pass
            t = threading.Timer(seconds, resume)
            t.daemon = True
            t.start()

    def wait(self, timeout_s: float) -> bool:
        try:
            self._proc.wait(timeout=timeout_s)
            return True
        except subprocess.TimeoutExpired:
            return False

    def close(self) -> None:
        if self.alive():
            self.terminate()
            if not self.wait(5.0):
                self.kill()
                self.wait(5.0)
        if self._proc.stdout is not None:
            try:
                self._proc.stdout.close()
            except OSError:
                pass


class LocalSidecar:
    """In-process sidecar stand-in (tests, and the bench's 1-sidecar
    reference): a real :class:`~logparser_tpu.service.ParseService` in
    THIS process, fronted over real sockets exactly like a child
    process would be.  ``kill()`` force-closes it (connections die
    mid-frame — the crash shape); ``suspend()`` stops its metrics
    endpoint (health probes go silent — the wedge shape)."""

    def __init__(self, index: int, **service_kwargs: Any):
        from .service import ParseService

        service_kwargs.setdefault("metrics_port", 0)
        self.index = index
        self._svc = ParseService(**service_kwargs).start()
        self.host = self._svc.host
        self.port = self._svc.port
        self.metrics_port = self._svc.metrics_port
        self._dead = False

    @property
    def pid(self) -> int:
        return os.getpid()

    @property
    def service(self):
        return self._svc

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        # Dead-by-flag first, teardown off-thread: a chaos kill fired
        # from a session thread must read as INSTANT death (the real
        # SIGKILL shape), not a blocking force-close join.
        self._dead = True
        threading.Thread(
            target=self._svc.shutdown,
            name=f"front-local-kill-{self.index}", daemon=True,
        ).start()

    def terminate(self) -> None:
        self._dead = True
        threading.Thread(
            target=lambda: self._svc.shutdown(drain=True),
            name=f"front-local-drain-{self.index}", daemon=True,
        ).start()

    def suspend(self, seconds: Optional[float] = None) -> None:
        if self._svc._metrics is not None:
            self._svc._metrics.shutdown()

    def wait(self, timeout_s: float) -> bool:
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            if self._svc._teardown_done.is_set():
                return True
            time.sleep(0.02)
        return self._svc._teardown_done.is_set()

    def close(self) -> None:
        self._dead = True
        self._svc.shutdown()


def parse_sidecar_address(address: str) -> Tuple[str, int, int]:
    """``host:port:metrics_port`` -> its parts.  The metrics port is
    REQUIRED: the supervisor's health probes (readyz + occupancy
    scrape) are the only liveness signal the front has for a process it
    does not own."""
    parts = str(address).rsplit(":", 2)
    if len(parts) != 3:
        raise ValueError(
            f"sidecar address {address!r} must be host:port:metrics_port"
        )
    host, port_s, mport_s = parts
    try:
        port, mport = int(port_s), int(mport_s)
    except ValueError:
        raise ValueError(
            f"sidecar address {address!r}: ports must be integers"
        ) from None
    if not host or not (0 < port < 65536 and 0 < mport < 65536):
        raise ValueError(f"sidecar address {address!r} out of range")
    return host, port, mport


class AdoptedSidecar:
    """A sidecar this front did NOT spawn: an already-running
    ``--sidecar`` service at ``host:port:metrics_port`` — possibly on
    another machine (ROADMAP 2c: the per-host-front seam the pod story
    composes with).  Adoption sits behind the exact supervisor probes a
    spawned child gets: readyz + /metrics scrape each heartbeat,
    wedge/fault detection, and "respawn" = RE-ADOPT (the constructor
    re-probes the address; while the remote is down the respawn fails
    and the prober keeps re-deciding — when the remote operator brings
    it back, the slot rejoins warm).

    Process-control primitives are no-ops by design: the front does not
    own the remote process, so ``kill``/``terminate``/``suspend`` do
    nothing, ``alive()`` is always True (scrape silence, not waitpid,
    is the death signal), and a roll of an adopted slot is just a
    re-probe — rolling the actual process belongs to its own host's
    operator."""

    def __init__(self, index: int, address: str,
                 connect_timeout_s: float = 3.0):
        self.index = index
        self.address_spec = str(address)
        self.host, self.port, self.metrics_port = parse_sidecar_address(
            address)
        # Reachability probe — adopt-or-fail, mirroring spawn-or-fail:
        # a clean connect + close (no CONFIG frame; the service reads a
        # zero-length session, which its accept loop treats as EOF).
        try:
            probe = socket.create_connection(
                (self.host, self.port), timeout=connect_timeout_s)
            probe.close()
        except OSError as e:
            raise SidecarSpawnError(
                f"sidecar {index}: cannot adopt {self.address_spec} "
                f"({e})"
            ) from e
        metrics().increment("front_sidecar_adoptions_total")

    @property
    def pid(self) -> int:
        return -1  # not ours; there is no local pid

    def alive(self) -> bool:
        return True

    def kill(self) -> None:
        pass

    def terminate(self) -> None:
        pass

    def suspend(self, seconds: Optional[float] = None) -> None:
        pass

    def wait(self, timeout_s: float) -> bool:
        return True

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# /metrics aggregation
# ---------------------------------------------------------------------------

_SAMPLE_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?( .+)$"
)
_COMMENT_FAMILY = re.compile(r"^# (?:TYPE|HELP) (\S+)")


def merge_expositions(own: str,
                      labeled: Sequence[Tuple[str, str]],
                      label: str = "sidecar") -> str:
    """One Prometheus text exposition for the whole fleet: the front's
    own families verbatim, then each sidecar's scrape with
    ``{label}="<name>"`` injected into every sample (docs/
    OBSERVABILITY.md "Fleet aggregation").  TYPE/HELP comments are
    emitted once per family across all sources (the validator requires
    a family's TYPE before its first sample; the declaration from the
    earliest source serves every later one)."""
    out: List[str] = []
    declared: set = set()
    for line in own.splitlines():
        m = _COMMENT_FAMILY.match(line)
        if m:
            declared.add(m.group(1))
        out.append(line)
    for name, text in labeled:
        inj = f'{label}="{name}"'
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                m = _COMMENT_FAMILY.match(line)
                if m is None or m.group(1) in declared:
                    continue
                declared.add(m.group(1))
                out.append(line)
                continue
            m = _SAMPLE_LINE.match(line)
            if m is None:
                continue  # never relay a malformed sidecar line
            fam, labels, rest = m.group(1), m.group(2), m.group(3)
            if labels:
                out.append(f"{fam}{{{labels[1:-1]},{inj}}}{rest}")
            else:
                out.append(f"{fam}{{{inj}}}{rest}")
    return "\n".join(out) + "\n"


def _scrape(url: str, timeout_s: float = 3.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


_GAUGE_RE_CACHE: Dict[str, re.Pattern] = {}


def _scrape_value(text: str, family: str) -> float:
    """Sum of one family's sample values in a scraped exposition."""
    pat = _GAUGE_RE_CACHE.get(family)
    if pat is None:
        pat = re.compile(
            r"^" + re.escape("logparser_tpu_" + family)
            + r"(?:\{[^}]*\})? (\S+)$", re.M,
        )
        _GAUGE_RE_CACHE[family] = pat
    return sum(float(v) for v in pat.findall(text))


# ---------------------------------------------------------------------------
# slots, tenants, routing
# ---------------------------------------------------------------------------


class _Slot:
    """One sidecar position in the fleet: the live handle plus the
    prober-maintained health/occupancy view the router reads."""

    def __init__(self, index: int):
        self.index = index
        self.name = f"sc{index}"
        self.handle: Optional[Any] = None
        self.generation = 0
        self.ready = False
        self.draining = False
        self.respawning = False
        self.last_ok = time.monotonic()
        self.occupancy = 0.0
        self.lock = threading.Lock()

    def address(self) -> Optional[Tuple[str, int]]:
        h = self.handle
        if h is None:
            return None
        return (h.host, h.port)


class _TenantLedger:
    """Per-tenant admission accounting (sessions + in-flight lines)."""

    def __init__(self, policy: FrontPolicy):
        self._policy = policy
        self._lock = threading.Lock()
        self._sessions: Dict[str, int] = {}
        self._lines: Dict[str, int] = {}

    def session_enter(self, tenant: str) -> bool:
        quota = self._policy.tenant_max_sessions
        with self._lock:
            n = self._sessions.get(tenant, 0)
            if quota and n >= quota:
                return False
            self._sessions[tenant] = n + 1
            return True

    def session_exit(self, tenant: str) -> None:
        with self._lock:
            n = self._sessions.get(tenant, 1) - 1
            if n > 0:
                self._sessions[tenant] = n
            else:
                self._sessions.pop(tenant, None)

    def lines_enter(self, tenant: str, n: int) -> bool:
        quota = self._policy.tenant_max_inflight_lines
        with self._lock:
            cur = self._lines.get(tenant, 0)
            if quota and cur + n > quota:
                return False
            self._lines[tenant] = cur + n
            return True

    def lines_exit(self, tenant: str, n: int) -> None:
        with self._lock:
            cur = self._lines.get(tenant, n) - n
            if cur > 0:
                self._lines[tenant] = cur
            else:
                self._lines.pop(tenant, None)


def key_label(parser_key: Any) -> str:
    """Short stable label for a parser cache key (metrics cardinality:
    8 hex chars, not the raw format string)."""
    return hashlib.blake2b(
        repr(parser_key).encode("utf-8"), digest_size=4
    ).hexdigest()


class _Router:
    """Rendezvous (HRW) affinity routing with occupancy spill: every
    (key, sidecar) pair gets a stable hash score; the ordered preference
    list only reshuffles the keys of a sidecar that LEAVES — exactly the
    property that keeps compiled-parser caches hot across membership
    churn."""

    def __init__(self, policy: FrontPolicy):
        self._policy = policy

    @staticmethod
    def _score(klabel: str, slot_name: str) -> bytes:
        return hashlib.blake2b(
            f"{klabel}:{slot_name}".encode("utf-8"), digest_size=8
        ).digest()

    def order(self, klabel: str, slots: Sequence[_Slot]) -> List[_Slot]:
        return sorted(
            slots, key=lambda s: self._score(klabel, s.name), reverse=True
        )

    def choose(self, klabel: str, candidates: Sequence[_Slot]
               ) -> Tuple[Optional[_Slot], bool]:
        """(chosen slot, spilled?) among routable candidates."""
        if not candidates:
            return None, False
        ordered = self.order(klabel, candidates)
        first = ordered[0]
        if (
            len(ordered) > 1
            and first.occupancy >= self._policy.spill_occupancy
            and ordered[1].occupancy < first.occupancy
        ):
            return ordered[1], True
        return first, False


def preferred_sidecar(parser_key: Any, n_sidecars: int) -> int:
    """Rendezvous first-choice sidecar INDEX for ``parser_key`` over a
    fully-healthy fleet of ``n_sidecars`` — computable statically
    (slot names are ``sc<i>``), which is how drills pick key sets that
    spread across the whole fleet deterministically."""
    kl = key_label(parser_key)
    best, best_score = 0, b""
    for i in range(n_sidecars):
        score = _Router._score(kl, f"sc{i}")
        if score > best_score:
            best, best_score = i, score
    return best


class _FleetPressure:
    """The fleet's aggregate occupancy as a process backpressure source:
    registered with :func:`logparser_tpu.feeder.register_backpressure_source`
    so the front's own admission leg (and anything else reading
    ``queue_backpressure()`` in this process) sees the sidecars'
    scraped coalesce-queue occupancy — backpressure aggregation ACROSS
    processes."""

    def __init__(self, front: "FrontTier"):
        self._front = front

    def backpressure(self) -> float:
        slots = [s for s in self._front._slots if s.ready]
        if not slots:
            return 0.0
        return min(1.0, min(s.occupancy for s in slots))

# ---------------------------------------------------------------------------
# the front tier
# ---------------------------------------------------------------------------


class _FrontServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler, front: "FrontTier"):
        super().__init__(addr, handler)
        self.front = front

    def handle_error(self, request, client_address) -> None:  # noqa: D102
        LOG.exception("front: unhandled session error from %s",
                      client_address)


def _read_raw_frame(sock: socket.socket, first_s: Optional[float],
                    rest_s: Optional[float],
                    max_frame: int = _MAX_FRAME,
                    payload_cap: Optional[int] = None
                    ) -> Tuple[str, bytes]:
    """One raw wire frame for RELAYING (never classifying):
    ``("eof", b"")`` on clean close or a length-0 frame,
    ``("error", text_bytes)`` for a marker + error-text pair,
    ``("data", payload)`` otherwise.  Raises :class:`_SessionTimeout` /
    ``ConnectionError`` / :class:`_FrameTooLarge` like the service's
    own reader — the proxy buffers whole frames so a mid-frame upstream
    death can still be answered with a STRUCTURED frame downstream."""
    header = _recv_exact_timed(sock, 4, first_s, rest_s)
    if header is None:
        return "eof", b""
    (length,) = struct.unpack(">I", header)
    if length == 0:
        return "eof", b""
    if length == _ERROR_MARKER:
        kind, payload = _read_raw_frame(sock, rest_s, rest_s, max_frame)
        if kind != "data":
            raise ConnectionError("error marker without its text frame")
        return "error", payload
    if length > max_frame:
        raise _FrameTooLarge(length, max_frame, fatal=True)
    if payload_cap is not None and length > payload_cap:
        raise _FrameTooLarge(length, payload_cap, fatal=True)
    payload = _recv_exact_timed(sock, length, rest_s, rest_s)
    if payload is None:
        raise ConnectionError(f"peer closed mid-frame (0/{length} bytes)")
    return "data", payload


class _FrontSessionHandler(socketserver.BaseRequestHandler):
    """One proxied session: CONFIG -> route by parser key -> relay
    frames, answering structured BUSY frames (never a reset) for every
    fleet-side failure mode."""

    server: _FrontServer

    def handle(self) -> None:  # noqa: D102 — socketserver contract
        front = self.server.front
        threading.current_thread().name = \
            f"front-sess-{next(front._session_seq)}"
        try:
            front._proxy_session(self.request)
        except Exception:  # noqa: BLE001 — a session must never kill/print
            LOG.exception("front: session failed")


class FrontTier:
    """The replicated front tier (module docstring; docs/SERVICE.md
    "Fleet").  ``spawner(index) -> handle`` builds one sidecar — the
    default spawns :class:`ProcessSidecar` children; tests and the
    bench inject :class:`LocalSidecar` (or stubs).  ``sidecar_args``
    ride every default-spawned child's CLI (version/config swaps roll
    through :meth:`roll`)."""

    def __init__(self, n_sidecars: int = 2, host: str = "127.0.0.1",
                 port: int = 0, metrics_port: Optional[int] = None,
                 policy: Optional[FrontPolicy] = None,
                 spawner: Optional[Callable[[int], Any]] = None,
                 sidecar_args: Sequence[str] = (),
                 sidecar_addresses: Sequence[str] = (),
                 warmup_fn: Optional[Callable[[Any], None]] = None,
                 chaos: Optional[Any] = None):
        self.policy = policy or FrontPolicy()
        # Remote sidecar ADOPTION (ROADMAP 2c): ``host:port:metrics_port``
        # addresses occupy the first len() slots (validated now, so a
        # typo fails construction, not a boot thread); any remaining
        # slots up to n_sidecars spawn local children as before.  The
        # supervisor treats both identically — probes, faults, circuit
        # breaking — except that "respawn" of an adopted slot re-probes
        # the address instead of forking a process.
        self._sidecar_addresses = [str(a) for a in sidecar_addresses]
        for a in self._sidecar_addresses:
            parse_sidecar_address(a)
        n_sidecars = max(n_sidecars, len(self._sidecar_addresses))
        self.supervisor = FrontSupervisor(self.policy, n_sidecars)
        # The supervisor is a PURE machine; the fleet serializes every
        # consultation (session threads + the prober race otherwise —
        # two racing routable() calls must not both win the one
        # half-open trial).
        self._sup_lock = threading.Lock()
        # Metric-label bounds: parser keys and tenant names are
        # CLIENT-CONTROLLED, and every distinct label value is a
        # persistent series in the process registry — an unauthenticated
        # peer looping unique CONFIGs must not grow the front's memory
        # (and its merged exposition) without bound.  First N distinct
        # values keep their own label; the tail aggregates as
        # "overflow".
        self._label_lock = threading.Lock()
        self._key_label_set: set = set()
        self._tenant_label_set: set = set()
        self.router = _Router(self.policy)
        self._tenants = _TenantLedger(self.policy)
        self._slots = [_Slot(i) for i in range(n_sidecars)]
        self._sidecar_args = list(sidecar_args)
        # Optional post-spawn warmup (handle -> None): runs BEFORE a
        # sidecar is marked routable — at boot, after a crash respawn,
        # and during a roll — so a replacement sidecar re-enters the
        # fleet with its parsers compiled instead of paying the cold
        # compile inside a client's request ("any sidecar can absorb a
        # key after one warmup", and this is the one warmup).
        self._warmup_fn = warmup_fn
        self._session_seq = itertools.count(1)
        self._session_slots = threading.BoundedSemaphore(
            self.policy.max_sessions)
        self._host = host
        self._spawner = spawner or self._default_spawner
        self._server = _FrontServer((host, port), _FrontSessionHandler,
                                    self)
        self._thread: Optional[threading.Thread] = None
        self._probers: List[threading.Thread] = []
        self._stop = threading.Event()
        self.draining = False
        self._closed = False
        self._close_lock = threading.Lock()
        self._roll_lock = threading.Lock()
        self._serving = False
        self._pressure = _FleetPressure(self)
        self._http: Optional["_FrontEndpoint"] = None
        if metrics_port is not None:
            self._http = _FrontEndpoint(host, metrics_port, self)
        from .tools.chaos import ChaosSpec, FrontChaos

        spec = chaos if isinstance(chaos, ChaosSpec) else (
            ChaosSpec.parse(chaos) if isinstance(chaos, str)
            else chaos)
        if spec is None:
            spec = ChaosSpec.from_env()
        self.chaos = FrontChaos(spec) if spec is not None else None

    # -- lifecycle -------------------------------------------------------

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def metrics_port(self) -> Optional[int]:
        return self._http.port if self._http is not None else None

    def sidecars(self) -> List[Tuple[str, str, int, Optional[int]]]:
        """Live (name, host, port, metrics_port) per sidecar — the warm
        path for drills that must pre-compile a format on every
        sidecar without going through affinity routing."""
        out = []
        for slot in self._slots:
            h = slot.handle
            if h is not None:
                out.append((slot.name, h.host, h.port, h.metrics_port))
        return out

    def _bounded_label(self, pool: set, value: str) -> str:
        with self._label_lock:
            if value in pool:
                return value
            if len(pool) < _MAX_METRIC_LABELS:
                pool.add(value)
                return value
            return "overflow"

    def _key_metric_label(self, klabel: str) -> str:
        return self._bounded_label(self._key_label_set, klabel)

    def _tenant_label(self, tenant: str) -> str:
        return self._bounded_label(self._tenant_label_set, tenant)

    def _default_spawner(self, index: int) -> Any:
        if index < len(self._sidecar_addresses):
            return AdoptedSidecar(
                index, self._sidecar_addresses[index],
                connect_timeout_s=self.policy.connect_timeout_s,
            )
        return ProcessSidecar(
            index, host=self._host, extra_args=self._sidecar_args,
            ready_timeout_s=self.policy.ready_timeout_s,
        )

    def _warm(self, handle: Any) -> None:
        if self._warmup_fn is None:
            return
        try:
            self._warmup_fn(handle)
        except Exception:  # noqa: BLE001 — a failed warmup is a slow
            # first request, not a dead sidecar.
            LOG.warning("front: warmup of sidecar %s failed; it joins "
                        "the fleet cold", getattr(handle, "index", "?"),
                        exc_info=True)

    def start(self) -> "FrontTier":
        """Spawn the fleet (in parallel — each sidecar pays a cold
        interpreter+jax start), then open the listener and the prober."""
        from .feeder import register_backpressure_source

        errors: List[BaseException] = []

        def boot(slot: _Slot) -> None:
            try:
                handle = self._spawner(slot.index)
                self._warm(handle)
                slot.handle = handle
                slot.ready = True
                slot.last_ok = time.monotonic()
                metrics().gauge_set("front_sidecar_ready", 1,
                                    labels={"sidecar": slot.name})
                if self.chaos is not None and self.chaos.on_ready(
                        slot.index):
                    handle.kill()  # flap_sidecar: die right at ready
            except BaseException as e:  # noqa: BLE001 — collected below
                errors.append(e)

        boots = [threading.Thread(target=boot, args=(s,), daemon=True)
                 for s in self._slots]
        for t in boots:
            t.start()
        for t in boots:
            t.join()
        if errors or not any(s.ready for s in self._slots):
            self.shutdown()
            raise SidecarSpawnError(
                f"fleet start failed: {errors or 'no sidecar ready'}"
            )
        register_backpressure_source(self._pressure)
        if self._http is not None:
            self._http.start()
        self._serving = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="front-accept",
            daemon=True,
        )
        self._thread.start()
        # One prober thread PER SLOT: a wedged sidecar's scrape blocks
        # its full timeout every beat, and a shared prober would let
        # one silent sidecar delay fault detection for the whole fleet.
        self._probers = [
            threading.Thread(
                target=self._probe_loop, args=(slot,),
                name=f"front-prober-{slot.name}", daemon=True,
            )
            for slot in self._slots
        ]
        for t in self._probers:
            t.start()
        LOG.info("front tier listening on %s:%d over %d sidecars",
                 self.host, self.port, len(self._slots))
        return self

    def __enter__(self) -> "FrontTier":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()

    def shutdown(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        from .feeder import deregister_backpressure_source

        self._stop.set()
        self.draining = True
        deregister_backpressure_source(self._pressure)
        if self._serving:
            self._server.shutdown()
        self._server.server_close()
        if self._http is not None:
            self._http.shutdown()
        for slot in self._slots:
            h = slot.handle
            if h is not None:
                try:
                    h.close()
                except Exception:  # noqa: BLE001 — teardown must finish
                    note_teardown(
                        LOG, "front_teardown_errors_total",
                        "sidecar_close",
                        f"sidecar {slot.name} close failed",
                    )
        for prober in self._probers:
            prober.join(timeout=5)
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                note_teardown(
                    LOG, "front_teardown_errors_total", "accept_join",
                    "front accept loop outlived its 5 s join",
                )

    # -- supervision -----------------------------------------------------

    def _probe_loop(self, slot: _Slot) -> None:
        while not self._stop.wait(self.policy.heartbeat_interval_s):
            try:
                self._probe_slot(slot)
            except Exception:  # noqa: BLE001 — the prober must survive
                LOG.debug("front: probe of %s failed", slot.name,
                          exc_info=True)

    def _probe_slot(self, slot: _Slot) -> None:
        handle = slot.handle
        if handle is None or slot.respawning or slot.draining:
            return
        now = time.monotonic()
        if not handle.alive():
            self._on_sidecar_fault(slot, "died")
            return
        try:
            text = _scrape(
                f"http://{handle.host}:{handle.metrics_port}/metrics",
                timeout_s=min(3.0, self.policy.heartbeat_deadline_s),
            )
            ready = 200 == self._readyz(handle)
        except Exception:  # noqa: BLE001 — silence is the signal
            if slot.ready and \
                    now - slot.last_ok > self.policy.heartbeat_deadline_s:
                # Alive, IN the rotation, and unresponsive past the
                # deadline: WEDGED.  Kill first so the respawn never
                # races a zombie holding the ports.  (A slot that is
                # not ready — still warming, mid-respawn — gets the
                # spawn path's own budget instead.)
                handle.kill()
                self._on_sidecar_fault(slot, "wedged")
            return
        slot.last_ok = now
        slot.ready = ready
        depth = _scrape_value(text, "service_coalesce_queue_depth")
        slot.occupancy = min(1.0, depth / max(1.0, float(
            self._sidecar_queue_depth())))
        metrics().gauge_set("front_sidecar_ready", 1.0 if ready else 0.0,
                            labels={"sidecar": slot.name})
        metrics().gauge_set("front_sidecar_occupancy", slot.occupancy,
                            labels={"sidecar": slot.name})

    def _sidecar_queue_depth(self) -> int:
        """The coalesce submission-queue bound the fleet's sidecars run
        with (the front spawns them, so it knows): the denominator of
        the scraped occupancy fraction."""
        args = self._sidecar_args
        for i, a in enumerate(args):
            if a == "--coalesce-queue-depth" and i + 1 < len(args):
                try:
                    return int(args[i + 1])
                except ValueError:
                    break
        from .service import ServiceLimits

        return ServiceLimits().coalesce_queue_depth

    @staticmethod
    def _readyz(handle: Any) -> int:
        import urllib.error

        try:
            with urllib.request.urlopen(
                f"http://{handle.host}:{handle.metrics_port}/readyz",
                timeout=3,
            ) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    def _on_sidecar_fault(self, slot: _Slot, kind: str) -> None:
        with slot.lock:
            # A draining slot is DELIBERATE churn (mid-roll): its
            # session failovers must not spawn a racing replacement —
            # the roll itself installs the successor.
            if slot.respawning or slot.draining or self._stop.is_set():
                return
            slot.respawning = True
        slot.ready = False
        metrics().gauge_set("front_sidecar_ready", 0,
                            labels={"sidecar": slot.name})
        now = time.monotonic()
        with self._sup_lock:
            decision = self.supervisor.on_fault(slot.index, now)
        if decision.circuit_opened:
            metrics().increment("front_circuit_open_total",
                                labels={"sidecar": slot.name})
            flight_event("front_circuit_open", sidecar=slot.name)
            LOG.warning("front: circuit OPEN around flapping sidecar %s",
                        slot.name)
        flight_event("front_sidecar_fault", sidecar=slot.name, fault=kind,
                     action=decision.action,
                     backoff_s=round(decision.backoff_s, 3))
        LOG.warning("front: sidecar %s fault (%s) -> %s (backoff %.2fs)",
                    slot.name, kind, decision.action, decision.backoff_s)
        if decision.action == "disable":
            log_warning_once(
                LOG,
                f"front: sidecar slot {slot.name} exhausted its restart "
                "budget and is DISABLED (a rolling restart revives it)",
            )
            slot.respawning = False
            return
        threading.Thread(
            target=self._respawn, args=(slot, decision.backoff_s),
            name=f"front-respawn-{slot.name}", daemon=True,
        ).start()

    def _respawn(self, slot: _Slot, backoff_s: float) -> None:
        try:
            if backoff_s and self._stop.wait(backoff_s):
                return
            old = slot.handle
            if old is not None:
                try:
                    old.close()
                except Exception:  # noqa: BLE001 — a corpse may resist
                    pass
            handle = self._spawner(slot.index)
            self._warm(handle)
            slot.handle = handle
            slot.generation += 1
            slot.last_ok = time.monotonic()
            slot.ready = True
            self.supervisor.total_restarts += 1
            metrics().increment("front_restarts_total",
                                labels={"sidecar": slot.name})
            metrics().gauge_set("front_sidecar_ready", 1,
                                labels={"sidecar": slot.name})
            LOG.info("front: sidecar %s respawned (generation %d)",
                     slot.name, slot.generation)
            if self.chaos is not None and self.chaos.on_ready(slot.index):
                handle.kill()  # flap_sidecar: die again at ready
        except Exception:  # noqa: BLE001 — the prober re-decides next beat
            LOG.exception("front: respawn of %s failed", slot.name)
            slot.last_ok = time.monotonic()  # restart the wedge clock
        finally:
            slot.respawning = False

    # -- rolling restart -------------------------------------------------

    def roll(self, drain_timeout_s: Optional[float] = None,
             sidecar_args: Optional[Sequence[str]] = None) -> None:
        """Zero-downtime rolling restart (docs/SERVICE.md "Fleet"): one
        sidecar at a time — routing stops handing it NEW sessions, its
        process drains gracefully under the round-12 machinery (readyz
        flip, admitted sessions finish, deadline escalation), a fresh
        one (optionally with new ``sidecar_args`` — the config/version
        swap) takes the slot, and only then does the next sidecar
        start.  The rest of the fleet absorbs the drained keys: with a
        retrying client, zero failed requests."""
        budget = (drain_timeout_s if drain_timeout_s is not None
                  else self.policy.drain_timeout_s)
        with self._roll_lock:
            if sidecar_args is not None:
                self._sidecar_args = list(sidecar_args)
            for slot in self._slots:
                if self._stop.is_set():
                    return
                LOG.info("front: rolling sidecar %s", slot.name)
                slot.draining = True
                # A fault-respawn already mid-flight finishes first (it
                # owns slot.handle until it clears the flag).
                wait_end = time.monotonic() + 30.0
                while slot.respawning and time.monotonic() < wait_end:
                    time.sleep(0.05)
                try:
                    old = slot.handle
                    if old is not None and old.alive():
                        old.terminate()
                        if not old.wait(budget):
                            LOG.warning(
                                "front: sidecar %s outlived its drain "
                                "budget; killing", slot.name)
                            old.kill()
                            old.wait(5.0)
                    if old is not None:
                        try:
                            old.close()
                        except Exception:  # noqa: BLE001
                            pass
                    handle = self._spawner(slot.index)
                    self._warm(handle)
                    slot.handle = handle
                    slot.generation += 1
                    slot.last_ok = time.monotonic()
                    slot.ready = True
                    with self._sup_lock:
                        self.supervisor.on_deliberate_restart(slot.index)
                    metrics().increment("front_rolls_total",
                                        labels={"sidecar": slot.name})
                    metrics().gauge_set("front_sidecar_ready", 1,
                                        labels={"sidecar": slot.name})
                finally:
                    slot.draining = False
                LOG.info("front: sidecar %s rolled (generation %d)",
                         slot.name, slot.generation)

    # -- routing + the proxy ---------------------------------------------

    def _routable_slots(self, now: float) -> List[_Slot]:
        with self._sup_lock:
            return [
                s for s in self._slots
                if s.ready and not s.draining and not s.respawning
                and s.handle is not None and s.handle.alive()
                and self.supervisor.routable(s.index, now)
            ]

    def _shed(self, sock: socket.socket, reason: str,
              tenant: Optional[str] = None) -> None:
        metrics().increment("front_shed_total", labels={"reason": reason})
        flight_event("front_shed", reason=reason, tenant=tenant)
        if tenant is not None:
            metrics().increment("front_tenant_shed_total",
                                labels={"tenant": self._tenant_label(
                                    tenant)})
        try:
            sock.settimeout(self.policy.idle_timeout_s)
            write_error(sock, busy_error_text(
                reason, self.policy.busy_retry_after_s))
            _linger_drain(sock)
        except OSError:
            pass

    def _failover(self, sock: socket.socket, slot: _Slot,
                  kind: str) -> None:
        """A dead/unreachable sidecar under a live client session: the
        structured answer (never a reset), the fault report, and the
        connection-level close the reason implies."""
        metrics().increment("front_failovers_total")
        metrics().increment("front_shed_total",
                            labels={"reason": "sidecar_failover"})
        flight_event("front_failover", sidecar=slot.name, fault=kind)
        LOG.warning("front: session failover off sidecar %s (%s)",
                    slot.name, kind)
        try:
            sock.settimeout(self.policy.idle_timeout_s)
            write_error(sock, busy_error_text(
                "sidecar_failover", self.policy.busy_retry_after_s))
            _linger_drain(sock)
        except OSError:
            pass

    def _proxy_session(self, sock: socket.socket) -> None:
        metrics().increment("front_sessions_total")
        if self.draining:
            self._shed(sock, "draining")
            return
        if not self._session_slots.acquire(blocking=False):
            self._shed(sock, "sessions")
            return
        try:
            self._proxy_admitted(sock)
        finally:
            self._session_slots.release()

    def _proxy_admitted(self, sock: socket.socket) -> None:
        pol = self.policy
        try:
            kind, config_raw = _read_raw_frame(
                sock, pol.idle_timeout_s, pol.frame_timeout_s,
                payload_cap=pol.max_config_bytes,
            )
        except (_SessionTimeout, _FrameTooLarge, ConnectionError,
                OSError) as e:
            LOG.info("front: config read failed: %s", e)
            return
        if kind != "data":
            return
        tenant = "default"
        send_stats = False
        config: Any = None
        parser_key: Any = ("raw", hashlib.blake2b(
            config_raw, digest_size=8).hexdigest())
        try:
            config = json.loads(config_raw)
            if isinstance(config, dict):
                tenant = str(config.get("tenant") or "default")
                send_stats = bool(config.get("stats"))
                parser_key = _ParserCache.key_of(config)
        except Exception:  # noqa: BLE001 — junk config still routes; the
            pass           # sidecar answers the structured config error
        klabel = key_label(parser_key)

        # Root session span (docs/OBSERVABILITY.md "Tracing"): a sampled
        # session gets the front's root context injected into the
        # relayed CONFIG — the ONLY case the config is re-serialized.
        # Unsampled sessions forward the client's RAW bytes untouched,
        # so an untraced session stays byte-identical on the wire
        # (golden protocol vectors replay unchanged).
        span = None
        if isinstance(config, dict):
            span = root_span("front_session",
                             traceparent=config.get("traceparent"),
                             attrs={"tenant": tenant, "key": klabel})
            if span is not None:
                config["traceparent"] = span.traceparent
                config_raw = json.dumps(config).encode("utf-8")

        # Tenant fairness + fleet backpressure at the front door.
        if not self._tenants.session_enter(tenant):
            if span is not None:
                span.end(outcome="shed", reason="tenant_quota")
            self._shed(sock, "tenant_quota", tenant=tenant)
            return
        try:
            from .feeder import queue_backpressure

            if queue_backpressure() >= pol.backpressure_threshold:
                if span is not None:
                    span.end(outcome="shed", reason="backpressure")
                self._shed(sock, "backpressure")
                return
            self._proxy_routed(sock, config_raw, klabel, tenant,
                               send_stats)
        finally:
            self._tenants.session_exit(tenant)
            if span is not None:
                span.end()

    def _connect_upstream(self, sock: socket.socket, klabel: str,
                          config_raw: bytes
                          ) -> Optional[Tuple[_Slot, socket.socket]]:
        """Route + connect + forward CONFIG, walking the rendezvous
        order through connect failures (each one a reported fault)."""
        pol = self.policy
        tried: set = set()
        while True:
            now = time.monotonic()
            candidates = [s for s in self._routable_slots(now)
                          if s.index not in tried]
            slot, spilled = self.router.choose(klabel, candidates)
            if slot is None:
                return None
            if spilled:
                metrics().increment("front_spills_total")
            addr = slot.address()
            if addr is None:
                tried.add(slot.index)
                continue
            try:
                up = socket.create_connection(
                    addr, timeout=pol.connect_timeout_s)
                up.settimeout(pol.upstream_timeout_s)
                write_frame(up, config_raw)
            except OSError:
                tried.add(slot.index)
                self._on_sidecar_fault(slot, "connect")
                continue
            with self._sup_lock:
                self.supervisor.on_success(slot.index, now)
            metrics().increment(
                "front_sessions_routed_total",
                labels={"key": self._key_metric_label(klabel),
                        "sidecar": slot.name},
            )
            if self.chaos is not None:
                action = self.chaos.on_routed(slot.index)
                if action == "kill":
                    slot.handle.kill()
                elif action == "wedge":
                    slot.handle.suspend(self.chaos.wedge_seconds(
                        slot.index))
            return slot, up

    def _proxy_routed(self, sock: socket.socket, config_raw: bytes,
                      klabel: str, tenant: str,
                      send_stats: bool) -> None:
        pol = self.policy
        routed = self._connect_upstream(sock, klabel, config_raw)
        if routed is None:
            self._shed(sock, "sidecar_failover")
            return
        slot, up = routed
        try:
            while True:
                try:
                    kind, payload = _read_raw_frame(
                        sock, pol.idle_timeout_s, pol.frame_timeout_s,
                    )
                except _SessionTimeout:
                    metrics().increment("front_timeouts_total",
                                        labels={"side": "client"})
                    return
                except (_FrameTooLarge, ConnectionError, OSError):
                    return
                if kind == "eof":
                    try:
                        up.sendall(struct.pack(">I", 0))
                    except OSError:
                        pass
                    return
                if kind == "error":
                    return  # a client must not send marker frames
                # Tenant in-flight-lines quota: the count prefix is the
                # first 4 payload bytes of a LINES frame.
                n_lines = struct.unpack(">I", payload[:4])[0] \
                    if len(payload) >= 4 else 0
                if not self._tenants.lines_enter(tenant, n_lines):
                    # Request-level tenant shed: a DISTINCT reason from
                    # the session-level ``tenant_quota`` — this one
                    # keeps the session, so the client must not burn a
                    # reconnect (RECONNECT_BUSY_REASONS) on it.
                    metrics().increment(
                        "front_tenant_shed_total",
                        labels={"tenant": self._tenant_label(tenant)})
                    metrics().increment(
                        "front_shed_total",
                        labels={"reason": "tenant_inflight"})
                    try:
                        sock.settimeout(pol.idle_timeout_s)
                        write_error(sock, busy_error_text(
                            "tenant_inflight", pol.busy_retry_after_s))
                    except OSError:
                        return
                    continue
                try:
                    if not self._relay_request(sock, up, slot, payload,
                                               send_stats):
                        return
                finally:
                    self._tenants.lines_exit(tenant, n_lines)
        finally:
            try:
                up.close()
            except OSError:
                pass

    def _relay_request(self, sock: socket.socket, up: socket.socket,
                       slot: _Slot, payload: bytes,
                       send_stats: bool) -> bool:
        """Forward one request frame and relay its response frame(s).
        False = the session must end (socket died, or a
        connection-level shed was relayed)."""
        pol = self.policy
        try:
            write_frame(up, payload)
            kind, body = _read_raw_frame(
                up, pol.upstream_timeout_s, pol.frame_timeout_s)
        except (_SessionTimeout, ConnectionError, OSError,
                _FrameTooLarge) as e:
            self._failover(sock, slot, f"{type(e).__name__}: {e}")
            self._on_sidecar_fault(slot, "relay")
            return False
        try:
            sock.settimeout(pol.idle_timeout_s)
            if kind == "eof":
                # The sidecar closed where a response was due: the
                # crash-mid-request shape.
                self._failover(sock, slot, "eof mid-request")
                self._on_sidecar_fault(slot, "relay")
                return False
            if kind == "error":
                sock.sendall(struct.pack(">I", _ERROR_MARKER))
                write_frame(sock, body)
                text = body.decode("utf-8", errors="replace")
                if text.startswith("BUSY"):
                    try:
                        reason = json.loads(text[4:].strip()).get("reason")
                    except Exception:  # noqa: BLE001 — junk JSON: keep open
                        reason = None
                    if reason in RECONNECT_BUSY_REASONS:
                        # The sidecar is closing the upstream by
                        # contract; mirror it downstream.
                        _linger_drain(sock)
                        return False
                return True
            write_frame(sock, body)
            if send_stats:
                kind, stats_body = _read_raw_frame(
                    up, pol.upstream_timeout_s, pol.frame_timeout_s)
                if kind != "data":
                    self._failover(sock, slot, "eof before STATS")
                    self._on_sidecar_fault(slot, "relay")
                    return False
                write_frame(sock, stats_body)
            metrics().increment("front_requests_relayed_total")
            return True
        except OSError:
            return False

    def stats(self) -> Dict[str, Any]:
        return {
            "sidecars": [
                {
                    "name": s.name,
                    "generation": s.generation,
                    "ready": s.ready,
                    "draining": s.draining,
                    "occupancy": round(s.occupancy, 4),
                }
                for s in self._slots
            ],
            "supervisor": self.supervisor.summary(),
        }


# ---------------------------------------------------------------------------
# the fleet HTTP endpoint: merged /metrics + health + /rollz
# ---------------------------------------------------------------------------


class _FrontHttpHandler(BaseHTTPRequestHandler):
    """GET /metrics -> the MERGED fleet exposition (front families +
    every live sidecar's scrape under a ``sidecar`` label); GET
    /tracez, /flightz -> the front's spans / flight events plus every
    live sidecar's, keyed by slot name; GET /healthz -> front liveness;
    GET /readyz -> 200 while >= 1 sidecar is ready (503 otherwise /
    while draining); POST /rollz -> trigger a background rolling
    restart (the loadgen ``--roll`` hook)."""

    server: ThreadingHTTPServer

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        front: FrontTier = self.server.front  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
        if path == "/metrics":
            scraped: List[Tuple[str, str]] = []
            for name, host, _port, mport in front.sidecars():
                if mport is None:
                    continue
                try:
                    scraped.append(
                        (name, _scrape(f"http://{host}:{mport}/metrics"))
                    )
                except Exception:  # noqa: BLE001 — a dead sidecar scrapes empty
                    continue
            body = merge_expositions(
                metrics().prometheus_text(), scraped
            ).encode("utf-8")
            self._respond(200, body,
                          "text/plain; version=0.0.4; charset=utf-8")
            return
        if path in ("/tracez", "/flightz"):
            # The fleet's trace/flight view in one scrape: the front's
            # own payload plus each live sidecar's, keyed by slot name
            # (a dead sidecar reports its scrape error instead).
            own = tracez_payload() if path == "/tracez" \
                else flightz_payload()
            sidecars: Dict[str, Any] = {}
            for name, host, _port, mport in front.sidecars():
                if mport is None:
                    continue
                try:
                    sidecars[name] = json.loads(
                        _scrape(f"http://{host}:{mport}{path}"))
                except Exception as e:  # noqa: BLE001 — dead sidecar
                    sidecars[name] = {"error": str(e)}
            body = json.dumps({"front": own, "sidecars": sidecars},
                              sort_keys=True).encode("utf-8")
            self._respond(200, body, "application/json")
            return
        if path in ("/healthz", "/readyz"):
            ready = [s.name for s in front._slots if s.ready]
            if path == "/healthz":
                status, code = "ok", 200
            elif front.draining or not ready:
                status, code = "draining" if front.draining \
                    else "no_sidecar", 503
            else:
                status, code = "ready", 200
            body = json.dumps({
                "status": status,
                "sidecars_ready": len(ready),
                "sidecars": len(front._slots),
            }, sort_keys=True).encode("utf-8")
            self._respond(code, body, "application/json")
            return
        self.send_error(404)

    def do_POST(self) -> None:  # noqa: N802
        front: FrontTier = self.server.front  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/rollz":
            threading.Thread(target=front.roll, name="front-roll",
                             daemon=True).start()
            body = json.dumps({"status": "rolling"}).encode("utf-8")
            self._respond(202, body, "application/json")
            return
        self.send_error(404)

    def _respond(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        LOG.debug("front http: " + fmt, *args)


class _FrontEndpoint:
    def __init__(self, host: str, port: int, front: FrontTier):
        self._server = ThreadingHTTPServer((host, port),
                                           _FrontHttpHandler)
        self._server.daemon_threads = True
        self._server.front = front  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="front-metrics",
            daemon=True,
        )
        self._thread.start()

    def shutdown(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m logparser_tpu.front``: run the front tier over N
    spawned sidecar processes.  SIGTERM shuts the front down; SIGHUP
    triggers a rolling restart of the fleet (also POST /rollz on the
    metrics port)."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8123)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="fleet /metrics + /readyz + POST /rollz port")
    ap.add_argument("--sidecars", type=int, default=2)
    ap.add_argument("--adopt", action="append", default=[],
                    metavar="HOST:PORT:METRICS_PORT",
                    help="adopt an already-running sidecar at this "
                         "address instead of spawning one (repeatable; "
                         "adopted addresses fill the first slots, "
                         "--sidecars still spawns the rest)")
    ap.add_argument("--tenant-max-sessions", type=int, default=0)
    ap.add_argument("--tenant-max-inflight-lines", type=int, default=0)
    ap.add_argument("--spill-occupancy", type=float, default=0.5)
    ap.add_argument("--heartbeat-deadline", type=float, default=5.0)
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent compile-cache directory "
                         "(docs/COMPILE.md) — exported as "
                         "LOGPARSER_TPU_COMPILE_CACHE to every spawned "
                         "sidecar, so respawns and rolling restarts warm "
                         "up by DESERIALIZING cached executables instead "
                         "of recompiling")
    ap.add_argument("--log-level", default=os.environ.get(
        "LOGPARSER_TPU_LOG_LEVEL", "INFO"))
    ap.add_argument("sidecar_args", nargs="*",
                    help="extra args passed through to every sidecar "
                         "(e.g. -- --request-deadline 5)")
    args = ap.parse_args(argv)
    if args.compile_cache:
        # Spawned sidecars inherit the front's environment (ProcessSidecar
        # copies os.environ), so one export here covers the whole fleet —
        # including every future respawn and rolling-restart replacement.
        from .tpu.compile_cache import ENV_CACHE_DIR

        os.environ[ENV_CACHE_DIR] = args.compile_cache
    logging.basicConfig(
        level=getattr(logging, str(args.log_level).upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    policy = FrontPolicy(
        tenant_max_sessions=args.tenant_max_sessions,
        tenant_max_inflight_lines=args.tenant_max_inflight_lines,
        spill_occupancy=args.spill_occupancy,
        heartbeat_deadline_s=args.heartbeat_deadline,
    )
    front = FrontTier(
        n_sidecars=args.sidecars, host=args.host, port=args.port,
        metrics_port=args.metrics_port, policy=policy,
        sidecar_args=args.sidecar_args,
        sidecar_addresses=args.adopt,
    )
    signal.signal(signal.SIGHUP,
                  lambda *_: threading.Thread(target=front.roll,
                                              daemon=True).start())
    stop = threading.Event()

    def _on_sigterm(*_: Any) -> None:
        # Crash-safe postmortem before the shutdown proceeds
        # (docs/OBSERVABILITY.md "Flight recorder").
        from .tracing import dump_flight

        flight_event("sigterm_shutdown")
        dump_flight("sigterm")
        stop.set()

    signal.signal(signal.SIGTERM, _on_sigterm)
    from .tracing import (
        arm_flight_signals,
        install_flight_excepthook,
        sweep_flight_dumps,
    )

    sweep_flight_dumps()
    arm_flight_signals()
    install_flight_excepthook()
    front.start()
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        front.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover — CLI
    raise SystemExit(main())
