"""Table loader with the string-only constructor protocol + projection pushdown.

Reference behavior: httpdlog-pigloader/.../Loader.java — Pig loaders only take
string parameters (:90-96): first parameter is the logformat; then
``-map:field:TYPE`` adds a type remapping (:105-119), ``-load:Class:param``
reflectively loads a dissector and configures it through
``initializeFromSettingsParameter`` (:121-149), ``fields`` switches to
metadata mode (:152-157), ``example`` generates a ready-to-paste script
(:159-164), anything else is a requested field (:166-168); no fields at all
means example mode (:176-180).  ``getNext`` emits tuples typed by casts
(:204-254); projection pushdown prunes requestedFields before parser
construction so unused tokens never get capture groups (:357-377, 441-447) —
here pushdown reaches the device program, which only computes requested
columns.
"""
from __future__ import annotations

import logging
import os

import importlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..core.casts import Cast
from ..core.fields import cleanup_field_value
from .inputformat import Counters, FIELDS_MAGIC, FileSplit, LogfileInputFormat

_MULTI_COMMENT = (
    "  # If you only want a single field replace * with name and use chararray"
)


def schema_entry(field_id: str, casts) -> Tuple[str, str]:
    """(column_name, type) for one ``TYPE:path`` request, driven by casts
    (Loader.java:380-412): long > double > chararray; wildcard -> map[].
    Shared by get_schema and the example generator so they cannot drift."""
    name = (
        field_id.split(":", 1)[1]
        .replace(".", "_")
        .replace("-", "_")
        .replace("*", "_")
    )
    if "*" in field_id:
        return name, "map[]"
    pig_type = "bytearray"
    if casts:
        if Cast.LONG in casts:
            pig_type = "long"
        elif Cast.DOUBLE in casts:
            pig_type = "double"
        elif Cast.STRING in casts:
            pig_type = "chararray"
    return name, pig_type


def load_dissector_by_name(class_path: str, param: str):
    """``module.submodule.ClassName`` -> configured dissector instance
    (the reflective ``-load:`` / ``load:`` protocol, Loader.java:121-149)."""
    module_name, _, class_name = class_path.rpartition(".")
    if not module_name:
        raise ValueError(
            f"Found load with bad specification: no module in {class_path!r}"
        )
    try:
        module = importlib.import_module(module_name)
    except ImportError as e:
        raise ValueError(
            f"Found load with bad specification: No such class:{class_path}"
        ) from e
    clazz = getattr(module, class_name, None)
    if clazz is None:
        raise ValueError(
            f"Found load with bad specification: No such class:{class_path}"
        )
    instance = clazz()
    if not instance.initialize_from_settings_parameter(param):
        raise ValueError(
            f"Initialization failed of dissector instance of class {class_path}"
        )
    return instance


class Loader:
    """String-configured batch table loader (Pig LoadFunc equivalent)."""

    def __init__(self, *parameters: str):
        from ..observability import log_version_banner_once

        # Loader construction is the Pig-side entry point (the reference
        # banners when the parser class loads into the Pig JVM).
        log_version_banner_once(logging.getLogger(__name__))
        self.log_format: Optional[str] = None
        self.requested_fields: List[str] = []
        self.type_remappings: Dict[str, Set[str]] = {}
        self.additional_dissectors: List[Any] = []
        self.special_parameters: List[str] = []
        self.only_want_list_of_fields = False
        self.is_building_example = False
        self.assembly_workers: Optional[int] = None
        self.counters = Counters()

        for param in parameters:
            if self.log_format is None:
                self.log_format = param
                continue
            if param.startswith("-map:"):
                parts = param.split(":")
                if len(parts) != 3:
                    raise ValueError(
                        f"Found map with wrong number of parameters:{param}"
                    )
                self.special_parameters.append(param)
                self.type_remappings.setdefault(parts[1], set()).add(parts[2])
                continue
            if param.startswith("-load:"):
                parts = param.split(":", 2)
                if len(parts) != 3:
                    raise ValueError(
                        f"Found load with wrong number of parameters:{param}"
                    )
                self.special_parameters.append(param)
                self.additional_dissectors.append(
                    load_dissector_by_name(parts[1], parts[2])
                )
                continue
            if param.startswith("-workers:"):
                # String-protocol extension (loaders only take strings,
                # Loader.java:90-96): host-side Arrow/record assembly
                # parallelism for the worker parser.
                value = param.split(":", 1)[1]
                if not value.isdigit() or int(value) < 1:
                    raise ValueError(
                        f"Found workers with bad parameter:{param}"
                    )
                self.special_parameters.append(param)
                self.assembly_workers = int(value)
                continue
            if param.lower() == FIELDS_MAGIC:
                self.only_want_list_of_fields = True
                self.requested_fields.append(FIELDS_MAGIC)
                continue
            if param.lower() == "example":
                self.is_building_example = True
                self.requested_fields.append(FIELDS_MAGIC)
                continue
            self.requested_fields.append(cleanup_field_value(param))

        if self.log_format is None:
            raise ValueError("Must specify the logformat")
        if not self.requested_fields:
            self.is_building_example = True
            self.requested_fields.append(FIELDS_MAGIC)

        self.input_format = LogfileInputFormat(
            self.log_format,
            self.requested_fields,
            type_remappings={k: set(v) for k, v in self.type_remappings.items()},
            extra_dissectors=list(self.additional_dissectors),
            assembly_workers=self.assembly_workers,
        )

    # ------------------------------------------------------------------

    def push_projection(self, required_fields: Sequence[str]) -> None:
        """Prune requested fields to the projected subset BEFORE parser
        construction — pushdown reaches the device split program, so pruned
        tokens are never captured (Loader.java:357-377, 441-447)."""
        required = [cleanup_field_value(f) for f in required_fields]
        unknown = [f for f in required if f not in self.requested_fields]
        if unknown:
            raise ValueError(f"Cannot project unknown fields: {unknown}")
        self.requested_fields = required
        self.input_format = LogfileInputFormat(
            self.log_format,
            self.requested_fields,
            type_remappings={k: set(v) for k, v in self.type_remappings.items()},
            extra_dissectors=list(self.additional_dissectors),
            assembly_workers=self.assembly_workers,
        )

    def _metadata_parser(self, targets: Optional[Sequence[str]] = None):
        from .inputformat import build_metadata_parser

        return build_metadata_parser(
            self.log_format,
            {k: set(v) for k, v in self.type_remappings.items()},
            list(self.additional_dissectors),
            targets=targets,
        )

    def get_schema(self) -> List[Tuple[str, str]]:
        """(column_name, type) per requested field, driven by casts
        (Loader.java:380-412): long > double > chararray; wildcard -> map."""
        if self.only_want_list_of_fields or self.is_building_example:
            return [(FIELDS_MAGIC, "chararray")]
        parser = self._metadata_parser(targets=self.requested_fields)
        return [
            schema_entry(field, parser.get_casts(field))
            for field in self.requested_fields
        ]

    # ------------------------------------------------------------------

    def load(self, path: str) -> Iterator[Tuple]:
        """Yield one tuple per line of ``path`` (getNext loop equivalent)."""
        if self.is_building_example:
            yield (self.create_example(),)
            return
        if self.only_want_list_of_fields:
            for fieldname in self.input_format.list_possible_fields():
                yield (fieldname,)
            return

        reader = self.input_format.create_record_reader(
            FileSplit(path, 0, os.path.getsize(path))
        )
        # Live-updating counters: available from the first yield, and still
        # correct when the caller stops consuming early.
        self.counters = reader.counters
        data_fields = [f for f in self.requested_fields]
        casts_of = {
            f: reader.parser.oracle.get_casts(f) for f in data_fields
        }
        for _, record in reader:
            values: List[Any] = []
            for field in data_fields:
                name = field.split(":", 1)[1]
                if field.endswith(".*"):
                    values.append(record.get_string_set(name[:-2]))
                    continue
                casts = casts_of.get(field)
                if casts and Cast.LONG in casts:
                    values.append(record.get_long(name))
                    continue
                if casts and Cast.DOUBLE in casts:
                    values.append(record.get_double(name))
                    continue
                values.append(record.get_string(name))
            yield tuple(values)

    # ------------------------------------------------------------------

    def create_example(self) -> str:
        """Ready-to-paste loader snippet listing every possible field with its
        schema type (createPigExample equivalent, Loader.java:260-333)."""
        paths = self._metadata_parser().get_possible_paths()
        all_parser = self._metadata_parser(targets=paths)

        fields: List[str] = []
        names: List[str] = []
        for value in paths:
            if "*" in value:
                fields.append(f"{value}',{_MULTI_COMMENT}")
            else:
                fields.append(value)
            name, cast = schema_entry(value, all_parser.get_casts(value))
            if "*" in value:
                cast = f"{cast},{_MULTI_COMMENT}"
            names.append(f"{name}:{cast}")

        specials = "".join(f"        {p!r},\n" for p in self.special_parameters)
        field_lines = ",\n".join(f"        {f!r}" for f in fields)
        name_lines = ",\n        ".join(names)
        return (
            "\n"
            "clicks = Loader(\n"
            f"        {self.log_format!r},\n\n"
            f"{specials}"
            f"{field_lines})\n"
            "    # AS (\n"
            f"    #     {name_lines});\n"
            "\n"
        )
