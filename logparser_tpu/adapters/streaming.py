"""Streaming operators: map-function / DoFn / bolt equivalents in micro-batch.

Reference behavior: the distribution contract is "config-serialization +
per-worker parser instantiation" (SURVEY §3.4): Flink builds the parser in
``RichMapFunction.open()`` (examples/apache-flink/.../TestParserMapFunctionInline),
Beam in ``DoFn`` setup, Storm in the bolt constructor
(examples/apache-storm/.../HttpdLoglineParserBolt.java).  All three are one
shape here: a serializable config object + a worker-side operator that lazily
builds its ``TpuBatchParser`` on first use and parses micro-batches on device.

Per-line fault tolerance matches the engines' skip-and-count policy, with the
Hive-style >1%-bad-after-1000-lines circuit breaker available opt-in
(ApacheHttpdlogDeserializer.java:120-126).
"""
from __future__ import annotations

import logging

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from .inputformat import Counters, records_from_result
from .record import ParsedRecord
from .serde import SerDeException, check_circuit_breaker

DEFAULT_MICRO_BATCH = 1024


@dataclass
class ParserConfig:
    """The serializable worker config (what the host engine ships)."""

    log_format: str
    fields: List[str]
    type_remappings: Dict[str, Any] = field(default_factory=dict)
    micro_batch_size: int = DEFAULT_MICRO_BATCH
    circuit_breaker: bool = False
    # Host-side Arrow assembly parallelism (None = auto); forwarded to
    # the worker parser so engine deployments can pin it per task slot.
    assembly_workers: Optional[int] = None

    def build_parser(self):
        from ..tpu.batch import TpuBatchParser

        return TpuBatchParser(
            self.log_format, self.fields,
            type_remappings=self.type_remappings,
            # The record surface never delivers string_view columns, so
            # the device never needs to emit Arrow view rows here.
            view_fields=(),
            assembly_workers=self.assembly_workers,
        )


class ParserMapOperator:
    """RichMapFunction / DoFn / bolt equivalent.

    ``open()`` builds the parser (lazily called); ``map(line)`` returns one
    ParsedRecord or None for a bad line; ``map_batch(lines)`` is the
    TPU-native bulk path the runner should prefer.
    """

    def __init__(self, config: ParserConfig):
        self.config = config
        self.parser = None
        self.counters = Counters()
        self._casts = None

    def open(self) -> None:
        if self.parser is None:
            from ..observability import log_version_banner_once

            # Worker-side operator startup (RichMapFunction.open / DoFn
            # setup / bolt prepare): banner once per worker process.
            log_version_banner_once(logging.getLogger(__name__))
            self.parser = self.config.build_parser()

    def close(self) -> None:
        self.parser = None

    # -- single-element surface (engine compatibility) ----------------------

    def map(self, line: Any) -> Optional[ParsedRecord]:
        records = self.map_batch([line])
        return records[0]

    # -- micro-batch surface (the fast path) --------------------------------

    def map_batch(self, lines: Sequence[Any]) -> List[Optional[ParsedRecord]]:
        self.open()
        result = self.parser.parse_batch(lines)
        return self._account(result)

    def map_batch_stream(
        self, batches: Iterator[Sequence[Any]], depth: int = 1
    ) -> Iterator[List[Optional[ParsedRecord]]]:
        """Batches-in-flight bulk path: up to ``depth`` micro-batches'
        device work stays dispatched ahead of the records being emitted,
        overlapping H2D/compute with host materialization
        (TpuBatchParser.parse_batch_stream).  Yields one record list per
        input batch, in order; counters update exactly as in
        :meth:`map_batch`, as each result is materialized."""
        self.open()
        for result in self.parser.parse_batch_stream(batches, depth=depth):
            yield self._account(result)

    def _account(self, result) -> List[Optional[ParsedRecord]]:
        if self._casts is None:
            self._casts = {
                fid: self.parser.oracle.get_casts(fid)
                for fid in self.parser.requested
            }
        self.counters.lines_read += result.lines_read
        self.counters.good_lines += result.good_lines
        self.counters.bad_lines += result.bad_lines
        if self.config.circuit_breaker:
            check_circuit_breaker(self.counters.bad_lines, self.counters.lines_read)

        # Bad lines become None entries: skip-and-count, never fatal per line.
        return records_from_result(result, self.parser.requested, self._casts)


class MicroBatcher:
    """Accumulates a stream into micro-batches for the operator.

    The Flink/Beam adapters' buffering step: feed lines one at a time, get
    (line, record) pairs out whenever a batch fills; ``flush()`` at the end
    of the stream / checkpoint barrier.
    """

    def __init__(self, operator: ParserMapOperator):
        self.operator = operator
        self._pending: List[Any] = []

    def feed(self, line: Any) -> List[Tuple[Any, Optional[ParsedRecord]]]:
        self._pending.append(line)
        if len(self._pending) >= self.operator.config.micro_batch_size:
            return self.flush()
        return []

    def flush(self) -> List[Tuple[Any, Optional[ParsedRecord]]]:
        if not self._pending:
            return []
        batch, self._pending = self._pending, []
        records = self.operator.map_batch(batch)
        return list(zip(batch, records))


def parse_stream(
    lines: Iterator[Any],
    config: ParserConfig,
    depth: int = 0,
) -> Iterator[Tuple[Any, Optional[ParsedRecord]]]:
    """End-to-end streaming helper: lines in, (line, record|None) out.

    ``depth=0`` (default) emits each micro-batch's records as soon as the
    batch fills — the right latency profile for LIVE sources (a tailed
    log that pauses must not hold finished records hostage to the next
    batch arriving).  ``depth>=1`` pipelines through
    ``map_batch_stream``: batch k's records are emitted while batch k+1
    computes on device, which raises throughput on BOUNDED sources
    (files, queues with backlog) at the cost of one batch of emission
    latency."""
    operator = ParserMapOperator(config)
    if depth <= 0:
        batcher = MicroBatcher(operator)
        for line in lines:
            yield from batcher.feed(line)
        yield from batcher.flush()
        return
    size = config.micro_batch_size

    def chunks():
        batch: List[Any] = []
        for line in lines:
            batch.append(line)
            if len(batch) >= size:
                yield batch
                batch = []
        if batch:
            yield batch

    pending: List[Sequence[Any]] = []

    def tee():
        for batch in chunks():
            pending.append(batch)
            yield batch

    for records in operator.map_batch_stream(tee(), depth=depth):
        batch = pending.pop(0)
        yield from zip(batch, records)
