"""Generic engine-facing record: typed maps + wildcard multi-values +
compact binary serialization.

Reference behavior: httpdlog-inputformat/.../ParsedRecord.java — string/long/
double maps, wildcard string-set maps keyed by a declared ``prefix.*``
registry (:40-57), and a custom Writable binary round-trip (write :60-96,
readFields :99-135).  The rebuild serializes with struct-packed
length-prefixed UTF-8 so records can cross process boundaries (shuffle
files, Arrow-adjacent sidecars) without pickle.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Set

_U32 = struct.Struct(">I")
_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")


def _pack_str(out: List[bytes], s: str) -> None:
    raw = s.encode("utf-8")
    out.append(_U32.pack(len(raw)))
    out.append(raw)


class _Cursor:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def u32(self) -> int:
        (v,) = _U32.unpack_from(self.buf, self.pos)
        self.pos += 4
        return v

    def i64(self) -> int:
        (v,) = _I64.unpack_from(self.buf, self.pos)
        self.pos += 8
        return v

    def f64(self) -> float:
        (v,) = _F64.unpack_from(self.buf, self.pos)
        self.pos += 8
        return v

    def string(self) -> str:
        n = self.u32()
        v = self.buf[self.pos : self.pos + n].decode("utf-8")
        self.pos += n
        return v


class ParsedRecord:
    """One parsed logline as typed name->value maps."""

    def __init__(self) -> None:
        self.strings: Dict[str, str] = {}
        self.longs: Dict[str, int] = {}
        self.doubles: Dict[str, float] = {}
        # wildcard support: declared "prefix" -> {full.name -> value}
        self.multi_prefixes: Set[str] = set()
        self.multi_strings: Dict[str, Dict[str, str]] = {}

    # -- population (the setter surface wired by the adapters) -------------

    def declare_requested_fieldname(self, fieldname: str) -> None:
        """Register a wildcard target (``prefix.*``) so later string sets
        under that prefix are captured as multi-values
        (ParsedRecord.java:40-49)."""
        if fieldname.endswith(".*"):
            self.multi_prefixes.add(fieldname[:-2])

    def _matching_prefix(self, name: str) -> Optional[str]:
        """The declared wildcard prefix this name falls under, if any.

        Matched against the declared registry (not derived by splitting the
        name): wildcard dissectors emit relative names that may themselves
        contain dots, e.g. query parameter ``utm.source`` under
        ``request.firstline.uri.query`` (ParsedRecord.java keys its multi
        maps by the declared prefix for the same reason)."""
        best = None
        for p in self.multi_prefixes:
            if name.startswith(p + ".") and (best is None or len(p) > len(best)):
                best = p
        return best

    def set_string(self, name: str, value: Optional[str]) -> None:
        if value is None:
            return
        self.strings[name] = value
        prefix = self._matching_prefix(name)
        if prefix is not None:
            self.multi_strings.setdefault(prefix, {})[name] = value

    def set_long(self, name: str, value: Optional[int]) -> None:
        if value is not None:
            self.longs[name] = value

    def set_double(self, name: str, value: Optional[float]) -> None:
        if value is not None:
            self.doubles[name] = value

    def set_multi_value_string(self, name: str, value: Optional[str]) -> None:
        if value is None:
            return
        prefix = self._matching_prefix(name)
        if prefix is None:
            prefix = name.rsplit(".", 1)[0] if "." in name else name
        self.multi_strings.setdefault(prefix, {})[name] = value

    # -- retrieval ----------------------------------------------------------

    def get_string(self, name: str) -> Optional[str]:
        return self.strings.get(name)

    def get_long(self, name: str) -> Optional[int]:
        return self.longs.get(name)

    def get_double(self, name: str) -> Optional[float]:
        return self.doubles.get(name)

    def get_string_set(self, prefix: str) -> Dict[str, str]:
        """All captured ``prefix.name -> value`` pairs for a wildcard target."""
        return dict(self.multi_strings.get(prefix, {}))

    def get(self, name: str) -> Any:
        for m in (self.strings, self.longs, self.doubles):
            if name in m:
                return m[name]
        return None

    def is_empty(self) -> bool:
        return not (self.strings or self.longs or self.doubles or self.multi_strings)

    def clear(self) -> None:
        self.strings.clear()
        self.longs.clear()
        self.doubles.clear()
        self.multi_strings.clear()

    # -- binary round-trip (Writable equivalent) ----------------------------

    def to_bytes(self) -> bytes:
        out: List[bytes] = []
        out.append(_U32.pack(len(self.strings)))
        for k, v in self.strings.items():
            _pack_str(out, k)
            _pack_str(out, v)
        out.append(_U32.pack(len(self.longs)))
        for k, lv in self.longs.items():
            _pack_str(out, k)
            out.append(_I64.pack(lv))
        out.append(_U32.pack(len(self.doubles)))
        for k, dv in self.doubles.items():
            _pack_str(out, k)
            out.append(_F64.pack(dv))
        out.append(_U32.pack(len(self.multi_prefixes)))
        for p in sorted(self.multi_prefixes):
            _pack_str(out, p)
        out.append(_U32.pack(len(self.multi_strings)))
        for p, kv in self.multi_strings.items():
            _pack_str(out, p)
            out.append(_U32.pack(len(kv)))
            for k, v in kv.items():
                _pack_str(out, k)
                _pack_str(out, v)
        return b"".join(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ParsedRecord":
        c = _Cursor(data)
        rec = cls()
        for _ in range(c.u32()):
            k = c.string()
            rec.strings[k] = c.string()
        for _ in range(c.u32()):
            k = c.string()
            rec.longs[k] = c.i64()
        for _ in range(c.u32()):
            k = c.string()
            rec.doubles[k] = c.f64()
        for _ in range(c.u32()):
            rec.multi_prefixes.add(c.string())
        for _ in range(c.u32()):
            p = c.string()
            kv: Dict[str, str] = {}
            for _ in range(c.u32()):
                k = c.string()
                kv[k] = c.string()
            rec.multi_strings[p] = kv
        return rec

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, ParsedRecord):
            return NotImplemented
        return (
            self.strings == other.strings
            and self.longs == other.longs
            and self.doubles == other.doubles
            and self.multi_prefixes == other.multi_prefixes
            and self.multi_strings == other.multi_strings
        )

    def __repr__(self) -> str:
        return (
            f"ParsedRecord(strings={self.strings!r}, longs={self.longs!r}, "
            f"doubles={self.doubles!r}, multi={self.multi_strings!r})"
        )
