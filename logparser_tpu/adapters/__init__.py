"""Engine adapters (L4): batch file input, table loader, row deserializer,
streaming operators.

Reference: httpdlog/httpdlog-{inputformat,pigloader,serde}/ — the rebuild
keeps the same string-configurable surfaces (SURVEY §5.6) on top of the TPU
batch path.
"""
from .inputformat import (
    CONFIG_KEY_FIELDS,
    CONFIG_KEY_FORMAT,
    Counters,
    FIELDS_MAGIC,
    FileSplit,
    LogfileInputFormat,
    LogfileRecordReader,
)
from .loader import Loader, load_dissector_by_name
from .record import ParsedRecord
from .serde import LogDeserializer, SerDeException
from .streaming import (
    MicroBatcher,
    ParserConfig,
    ParserMapOperator,
    parse_stream,
)

__all__ = [
    "CONFIG_KEY_FIELDS",
    "CONFIG_KEY_FORMAT",
    "Counters",
    "FIELDS_MAGIC",
    "FileSplit",
    "LogfileInputFormat",
    "LogfileRecordReader",
    "Loader",
    "LogDeserializer",
    "MicroBatcher",
    "ParsedRecord",
    "ParserConfig",
    "ParserMapOperator",
    "SerDeException",
    "load_dissector_by_name",
    "parse_stream",
]
