"""Apache Flink (PyFlink) binding: real ``MapFunction``/``FlatMapFunction``
shells over the micro-batch operator.

The reference's Flink example builds the parser in
``RichMapFunction.open()`` and maps one record per log line
(examples/apache-flink/.../TestParserMapFunctionInline.java);
``ParseLogLineMap`` is that exact shape for PyFlink's DataStream API.

``ParseLogLinesFlatMap`` adds micro-batching on top (buffer
``micro_batch_size`` lines, parse through the TPU batch path, emit the
good records).  One honest caveat, stated rather than hidden: Flink's
operator lifecycle gives ``close()`` no collector, so the records still
buffered at end-of-input CANNOT be emitted into the stream from there.
``close()`` parses them anyway — counters stay exact — and exposes them
as ``tail_records`` / via ``flush_remaining()`` for bounded jobs that
drain results themselves.  In an unbounded topology, either size
``micro_batch_size`` to your latency budget or use the per-record
``ParseLogLineMap``.

``pyflink`` is an OPTIONAL dependency: importing this module without it
works; constructing a function raises with install guidance.

Usage::

    from pyflink.datastream import StreamExecutionEnvironment
    from logparser_tpu.adapters import ParserConfig
    from logparser_tpu.adapters.flink import ParseLogLinesFlatMap

    env = StreamExecutionEnvironment.get_execution_environment()
    (env.from_source(...)
        .flat_map(ParseLogLinesFlatMap(ParserConfig("combined", FIELDS)))
        ...)
"""
from __future__ import annotations

from typing import Any, List, Optional

from .record import ParsedRecord
from .streaming import MicroBatcher, ParserConfig, ParserMapOperator

try:  # pragma: no cover - exercised via the fake-module tests
    from pyflink.datastream.functions import FlatMapFunction, MapFunction
    _HAVE_FLINK = True
except ImportError:  # pragma: no cover
    MapFunction = object
    FlatMapFunction = object
    _HAVE_FLINK = False


def flink_available() -> bool:
    return _HAVE_FLINK


def _require_flink(cls_name: str) -> None:
    if not _HAVE_FLINK:
        raise ImportError(
            f"pyflink is not installed; `pip install apache-flink` to use "
            f"{cls_name} (the engine-agnostic equivalent is "
            "logparser_tpu.adapters.streaming.ParserMapOperator)"
        )


class ParseLogLineMap(MapFunction):
    """``MapFunction``: one line -> ParsedRecord or None (bad line).

    The literal shape of the reference's RichMapFunction example; use
    :class:`ParseLogLinesFlatMap` when throughput matters — per-element
    mapping pays a device round-trip per line.
    """

    def __init__(self, config: ParserConfig):
        _require_flink(type(self).__name__)
        self.config = config
        self._operator: Optional[ParserMapOperator] = None

    def open(self, runtime_context=None):
        self._operator = ParserMapOperator(self.config)
        self._operator.open()

    def close(self):
        if self._operator is not None:
            self._operator.close()
            self._operator = None

    def map(self, value: Any) -> Optional[ParsedRecord]:
        if self._operator is None:
            self.open()
        return self._operator.map(value)


class ParseLogLinesFlatMap(FlatMapFunction):
    """``FlatMapFunction`` with micro-batching over
    :class:`~logparser_tpu.adapters.streaming.MicroBatcher` (ONE batching
    implementation, not a re-implementation): lines buffer to
    ``config.micro_batch_size`` and parse through the TPU batch path;
    good records are emitted, bad lines are skipped and counted.

    End-of-input: see the module docstring — ``close()`` parses the
    buffered tail (counters exact) into :attr:`tail_records`;
    :meth:`flush_remaining` yields the tail (buffered + already-parsed)
    for bounded jobs that drain manually.
    """

    def __init__(self, config: ParserConfig):
        _require_flink(type(self).__name__)
        self.config = config
        self._operator: Optional[ParserMapOperator] = None
        self._batcher: Optional[MicroBatcher] = None
        self.tail_records: List[ParsedRecord] = []

    def open(self, runtime_context=None):
        self._operator = ParserMapOperator(self.config)
        self._operator.open()
        self._batcher = MicroBatcher(self._operator)
        self.tail_records = []

    def close(self):
        # No collector here (Flink lifecycle): parse the tail so the
        # counters are exact and the records are recoverable.
        if self._batcher is not None:
            self.tail_records.extend(
                rec for _, rec in self._batcher.flush() if rec is not None
            )

    def flat_map(self, value: Any):
        if self._batcher is None:
            self.open()
        for _, record in self._batcher.feed(value):
            if record is not None:
                yield record

    def flush_remaining(self):
        """Parse + yield every record not yet emitted: the current buffer
        plus any tail ``close()`` already parsed.  Call when draining a
        bounded stream manually (before or after close — both work, no
        line is parsed twice or dropped)."""
        if self._batcher is not None:
            self.tail_records.extend(
                rec for _, rec in self._batcher.flush() if rec is not None
            )
        tail, self.tail_records = self.tail_records, []
        yield from tail

    @property
    def counters(self):
        return self._operator.counters if self._operator else None


__all__ = [
    "ParseLogLineMap",
    "ParseLogLinesFlatMap",
    "ParsedRecord",
    "flink_available",
]
