"""Row deserializer with properties-based config + bad-line circuit breaker.

Reference behavior: httpdlog-serde/.../ApacheHttpdlogDeserializer.java —
SERDEPROPERTIES protocol ``logformat``, ``field:<column>`` -> path,
``map:<field>`` -> type remap, ``load:<class>`` -> param (:136-187); column
types STRING/BIGINT/DOUBLE wired to typed setters (:228-245); error policy:
tolerate bad lines (return None), abort when >1% bad after >=1000 lines
(:120-126, 284-289).

TPU-native addition: ``deserialize_batch`` pushes whole micro-batches through
the fused device program; ``deserialize`` keeps the reference's one-line
surface on top of it.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core.casts import Cast
from ..tpu.batch import TpuBatchParser
from .loader import load_dissector_by_name

# Hive column type names (serdeConstants).
STRING_TYPE = "string"
BIGINT_TYPE = "bigint"
DOUBLE_TYPE = "double"

_MINIMAL_FAIL_LINES = 1000
_MINIMAL_FAIL_PERCENTAGE = 1


class SerDeException(Exception):
    pass


def check_circuit_breaker(lines_bad: int, lines_input: int) -> None:
    """The Hive >1%-bad-after->=1000-lines abort policy
    (ApacheHttpdlogDeserializer.java:120-126, 284-289), shared by the serde
    and the streaming operators."""
    if lines_input >= _MINIMAL_FAIL_LINES:
        if 100 * lines_bad > _MINIMAL_FAIL_PERCENTAGE * lines_input:
            raise SerDeException(
                f"To many bad lines: {lines_bad} of {lines_input} are bad."
            )


class LogDeserializer:
    """Properties-configured line -> row deserializer (Hive SerDe equivalent)."""

    def __init__(self, properties: Dict[str, str]):
        log_format = properties.get("logformat")
        if not log_format:
            raise SerDeException("Must specify the logformat")

        type_remappings: Dict[str, set] = {}
        extra_dissectors: List[Any] = []
        for key, value in properties.items():
            if key.startswith("map:"):
                type_remappings.setdefault(key[len("map:"):], set()).add(value)
            elif key.startswith("load:"):
                try:
                    extra_dissectors.append(
                        load_dissector_by_name(key[len("load:"):], value)
                    )
                except ValueError as e:
                    raise SerDeException(str(e)) from e

        columns_prop = properties.get("columns", "")
        types_prop = properties.get("columns.types", "")
        column_names = [c.strip() for c in columns_prop.split(",") if c.strip()]
        column_types = [t.strip() for t in types_prop.split(",") if t.strip()]
        if len(column_names) != len(column_types):
            raise SerDeException(
                f"columns ({len(column_names)}) and columns.types "
                f"({len(column_types)}) must have the same arity"
            )

        self.columns: List[Tuple[str, str, str]] = []  # (name, type, fieldpath)
        usable = True
        fields: List[str] = []
        for name, ctype in zip(column_names, column_types):
            field_value = properties.get(f"field:{name}")
            if field_value is None:
                usable = False
                continue
            if ctype not in (STRING_TYPE, BIGINT_TYPE, DOUBLE_TYPE):
                usable = False
                continue
            self.columns.append((name, ctype, field_value))
            fields.append(field_value)
        if not usable:
            raise SerDeException(
                "Fatal config error. Check the logged error messages why."
            )

        self.parser = TpuBatchParser(
            log_format,
            fields,
            type_remappings=type_remappings,
            extra_dissectors=extra_dissectors,
            # Row-object delivery: device Arrow view rows are never read.
            view_fields=(),
        )
        self._field_ids = list(self.parser.requested)
        self.lines_input = 0
        self.lines_bad = 0

    # ------------------------------------------------------------------

    def _coerce_row(self, values: Dict[str, Any]) -> List[Any]:
        row: List[Any] = []
        for (name, ctype, _), fid in zip(self.columns, self._field_ids):
            v = values.get(fid)
            if v is None:
                row.append(None)
            elif ctype == BIGINT_TYPE:
                try:
                    row.append(int(v))
                except (TypeError, ValueError):
                    row.append(None)
            elif ctype == DOUBLE_TYPE:
                try:
                    row.append(float(v))
                except (TypeError, ValueError):
                    row.append(None)
            else:
                row.append(str(v))
        return row

    def _check_circuit_breaker(self) -> None:
        check_circuit_breaker(self.lines_bad, self.lines_input)

    def deserialize_batch(self, lines: Sequence[Any]) -> List[Optional[List[Any]]]:
        """Micro-batch path: one fused device run for the whole batch;
        bad lines yield None rows and feed the circuit breaker."""
        result = self.parser.parse_batch(lines)
        self.lines_input += result.lines_read
        self.lines_bad += result.bad_lines

        columns = {fid: result.to_pylist(fid) for fid in self._field_ids}
        rows: List[Optional[List[Any]]] = []
        for i in range(result.lines_read):
            if not result.valid[i]:
                rows.append(None)
                self._check_circuit_breaker()
                continue
            values = {fid: columns[fid][i] for fid in self._field_ids}
            rows.append(self._coerce_row(values))
        return rows

    def deserialize(self, line: Any) -> Optional[List[Any]]:
        """One line -> row list (or None for a tolerated bad line)."""
        return self.deserialize_batch([line])[0]
