"""Apache Beam binding: a real ``beam.DoFn`` over the micro-batch operator.

The reference ships engine-native classes users drop into their pipelines
(examples/apache-beam/.../TestParserDoFnInline.java builds the parser in
``DoFn.setup`` and parses per element).  This module is the drop-in
equivalent for Beam's Python SDK — a thin shell over
:class:`~logparser_tpu.adapters.streaming.ParserMapOperator`.

Batching discipline: the DoFn does NOT buffer across ``process`` calls —
holding elements and re-emitting them later would detach them from their
window/timestamp (a windowed pipeline would then aggregate records into
the wrong window).  Instead it accepts BATCH elements: put Beam's own
``BatchElements`` transform in front, which batches within windows
correctly, and every output record inherits its input batch's window.
Single-line elements also work (a batch of one — correct, just slower).

``apache_beam`` is an OPTIONAL dependency: importing this module without it
works (so the package surface is always present); constructing the DoFn
raises with install guidance.  Nothing else in logparser_tpu depends on it.

Usage::

    import apache_beam as beam
    from logparser_tpu.adapters import ParserConfig
    from logparser_tpu.adapters.beam import ParseLogLinesDoFn

    with beam.Pipeline() as p:
        (p | beam.io.ReadFromText("access.log")
           | beam.BatchElements(min_batch_size=256, max_batch_size=4096)
           | beam.ParDo(ParseLogLinesDoFn(ParserConfig("combined", FIELDS)))
           | ...)

Each output element is a ``ParsedRecord`` (bad lines are skipped and
counted, the engines' skip-and-count policy).
"""
from __future__ import annotations

from typing import Optional

from .record import ParsedRecord
from .streaming import ParserConfig, ParserMapOperator

try:  # pragma: no cover - exercised via the fake-module tests
    import apache_beam as _beam
    _DoFnBase = _beam.DoFn
    _HAVE_BEAM = True
except ImportError:  # pragma: no cover
    _beam = None
    _DoFnBase = object
    _HAVE_BEAM = False


def beam_available() -> bool:
    return _HAVE_BEAM


class ParseLogLinesDoFn(_DoFnBase):
    """``beam.DoFn``: batches of log lines in, ParsedRecords out.

    The parser is built once per worker in ``setup`` (the config object
    is what Beam serializes to workers).  Each ``process`` element may be
    a list/tuple of lines (the ``BatchElements`` shape — preferred) or a
    single line; outputs are emitted inside the same ``process`` call, so
    they keep the element's window and timestamp.
    """

    def __init__(self, config: ParserConfig):
        if not _HAVE_BEAM:
            raise ImportError(
                "apache_beam is not installed; "
                "`pip install apache-beam` to use ParseLogLinesDoFn "
                "(the engine-agnostic equivalent is "
                "logparser_tpu.adapters.streaming.ParserMapOperator)"
            )
        super().__init__()
        self.config = config
        self._operator: Optional[ParserMapOperator] = None

    # -- beam lifecycle --------------------------------------------------

    def setup(self):
        self._operator = ParserMapOperator(self.config)
        self._operator.open()

    def process(self, element):
        # Only LISTS are batches (the BatchElements shape).  Tuples are
        # deliberately NOT treated as batches: a KV element like
        # ("key", "line") would otherwise silently parse its key as a
        # log line.
        batch = element if isinstance(element, list) else [element]
        for record in self._operator.map_batch(list(batch)):
            if record is not None:  # skip-and-count: bad lines drop
                yield record

    def teardown(self):
        if self._operator is not None:
            self._operator.close()
            self._operator = None

    @property
    def counters(self):
        """The operator's line counters (lines_read/good/bad)."""
        return self._operator.counters if self._operator else None


__all__ = ["ParseLogLinesDoFn", "ParsedRecord", "beam_available"]
