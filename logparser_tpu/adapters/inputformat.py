"""Batch logfile input format: splits -> micro-batched TPU parsing -> records.

Reference behavior: httpdlog-inputformat/.../ApacheHttpdLogfileInputFormat.java
(config carrier + split factory) and ApacheHttpdLogfileRecordReader.java —
line reading per split (:57), config keys (:124-131), counters "Lines read"/
"Good lines"/"Bad lines" (:118-120), bad lines skipped not fatal with error
logging capped at 10 (:228-280), magic field list ``fields`` switching to a
metadata mode that emits every possible path instead of data (:166-175,
233-244), wildcard ``.*`` targets delivered via setMultiValueString
(:205-217).

TPU-native redesign: instead of one line at a time through a regex, the
reader accumulates a micro-batch per split and runs it through
``TpuBatchParser.parse_batch`` (fused device program + host fallback), then
streams ``ParsedRecord``s out.  Split semantics mirror Hadoop's
LineRecordReader: a split that does not start at byte 0 skips the first
(partial) line; every split reads through the end of the last line that
STARTS inside it.
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..core.casts import Cast
from ..tpu.batch import TpuBatchParser
from .record import ParsedRecord

LOG = logging.getLogger(__name__)

# Hadoop-style string-config keys (the reference reads
# nl.basjes.parse.apachehttpdlogline.{format,fields},
# ApacheHttpdLogfileRecordReader.java:124-131).
CONFIG_KEY_FORMAT = "logparser.tpu.format"
CONFIG_KEY_FIELDS = "logparser.tpu.fields"
# Accepted aliases so reference configs keep working verbatim.
_REFERENCE_KEY_FORMAT = "nl.basjes.parse.apachehttpdlogline.format"
_REFERENCE_KEY_FIELDS = "nl.basjes.parse.apachehttpdlogline.fields"

FIELDS_MAGIC = "fields"  # metadata mode trigger (RecordReader :166-175)
MAX_LOGGED_ERRORS = 10   # error-log cap (RecordReader :228-267)
DEFAULT_BATCH = 4096


def set_typed_value(record: "ParsedRecord", name: str, value: Any, casts) -> None:
    """Deliver one value through the record's typed setters, driven by the
    producing dissector's casts — the same routing the reference gets by
    registering one setter per cast (RecordReader :205-217).  String values
    from the host path are coerced to the numeric cast when they parse."""
    if casts and Cast.LONG in casts:
        try:
            record.set_long(name, int(value))
            return
        except (TypeError, ValueError):
            pass
    if casts and Cast.DOUBLE in casts:
        try:
            record.set_double(name, float(value))
            return
        except (TypeError, ValueError):
            pass
    record.set_string(name, str(value))


def records_from_result(result, requested, casts_by_field) -> List[Optional["ParsedRecord"]]:
    """Columnar BatchResult -> one ParsedRecord per line (None = bad line).

    The single record-assembly path shared by the file reader and the
    streaming operators: declares wildcard prefixes, expands ``.*`` dicts
    through the multi-value setter, and routes scalars through
    :func:`set_typed_value`.
    """
    columns = {fid: result.to_pylist(fid) for fid in requested}
    out: List[Optional[ParsedRecord]] = []
    for i in range(result.lines_read):
        if not result.valid[i]:
            out.append(None)
            continue
        record = ParsedRecord()
        for fid in requested:
            name = fid.split(":", 1)[1]
            record.declare_requested_fieldname(name)
            value = columns[fid][i]
            if value is None:
                continue
            if name.endswith(".*"):
                base = name[:-2]
                for rel, v in value.items():
                    record.set_multi_value_string(f"{base}.{rel}", v)
            else:
                set_typed_value(record, name, value, casts_by_field.get(fid))
        out.append(record)
    return out


def build_metadata_parser(
    log_format: str,
    type_remappings: Optional[Dict[str, Any]] = None,
    extra_dissectors: Optional[Sequence[Any]] = None,
    targets: Optional[Sequence[str]] = None,
):
    """Host parser for discovery surfaces (possible paths, casts) — no batch
    compilation, optionally assembled over explicit targets for get_casts."""
    from ..httpd.parser import HttpdLoglineParser
    from ..tpu.batch import _CollectingRecord

    parser = HttpdLoglineParser(_CollectingRecord, log_format)
    parser.apply_config(type_remappings, extra_dissectors)
    if targets:
        parser.add_parse_target("set_value", list(targets))
        parser.assemble_dissectors()
    return parser


@dataclass
class FileSplit:
    """One byte-range of one file (FileInputFormat split equivalent)."""

    path: str
    start: int
    length: int

    @property
    def end(self) -> int:
        return self.start + self.length


@dataclass
class Counters:
    """The reference's Hadoop counter trio (RecordReader :118-120)."""

    lines_read: int = 0
    good_lines: int = 0
    bad_lines: int = 0

    def as_dict(self) -> Dict[str, int]:
        return {
            "Lines read": self.lines_read,
            "Good lines": self.good_lines,
            "Bad lines": self.bad_lines,
        }


class LogfileInputFormat:
    """Carries the parse config; makes splits and record readers."""

    def __init__(
        self,
        log_format: Optional[str] = None,
        requested_fields: Optional[Sequence[str]] = None,
        type_remappings: Optional[Dict[str, Any]] = None,
        extra_dissectors: Optional[Sequence[Any]] = None,
        batch_size: int = DEFAULT_BATCH,
        assembly_workers: Optional[int] = None,
    ):
        from ..observability import log_version_banner_once

        # Engine entry point: the reference banners once per JVM when the
        # first parser component loads (HttpdLoglineParser.java:54-94).
        log_version_banner_once(LOG)
        self.log_format = log_format
        self.requested_fields = list(requested_fields or [])
        self.type_remappings = dict(type_remappings or {})
        self.extra_dissectors = list(extra_dissectors or [])
        self.batch_size = batch_size
        # Host-side delivery parallelism, forwarded to the shared parser
        # (None = auto).
        self.assembly_workers = assembly_workers

    @classmethod
    def from_config(cls, config: Dict[str, str], **kwargs) -> "LogfileInputFormat":
        """Build from a string-only config map (the Hadoop Configuration
        surface; both native and reference key names accepted)."""
        log_format = config.get(CONFIG_KEY_FORMAT) or config.get(
            _REFERENCE_KEY_FORMAT
        )
        fields_str = config.get(CONFIG_KEY_FIELDS) or config.get(
            _REFERENCE_KEY_FIELDS, ""
        )
        fields = [f.strip() for f in fields_str.split(",") if f.strip()]
        return cls(log_format, fields, **kwargs)

    def list_possible_fields(self) -> List[str]:
        """All possible paths for the configured format
        (ApacheHttpdLogfileInputFormat.listPossibleFields equivalent)."""
        parser = build_metadata_parser(
            self.log_format, self.type_remappings, self.extra_dissectors
        )
        return parser.get_possible_paths()

    def get_splits(self, path: str, split_size: int = 64 * 1024 * 1024) -> List[FileSplit]:
        size = os.path.getsize(path)
        if size == 0:
            return []
        splits = []
        offset = 0
        while offset < size:
            length = min(split_size, size - offset)
            splits.append(FileSplit(path, offset, length))
            offset += length
        return splits

    def create_record_reader(self, split: FileSplit) -> "LogfileRecordReader":
        return LogfileRecordReader(self, split)

    def shared_parser(self) -> TpuBatchParser:
        """One TpuBatchParser per input format, shared by every split's
        reader: the parse config is identical across splits, and a fresh
        parser per split would re-assemble the oracle and re-JIT the device
        program (first TPU compile is tens of seconds) once per split."""
        parser = getattr(self, "_shared_parser", None)
        if parser is None:
            parser = TpuBatchParser(
                self.log_format,
                self.requested_fields,
                type_remappings=self.type_remappings,
                extra_dissectors=self.extra_dissectors,
                # Record readers deliver ParsedRecords, never string_view
                # Arrow columns: device view emission is pure waste here.
                view_fields=(),
                assembly_workers=self.assembly_workers,
            )
            self._shared_parser = parser
        return parser


class LogfileRecordReader:
    """Reads one split, parses micro-batches on device, yields ParsedRecords."""

    def __init__(self, input_format: LogfileInputFormat, split: FileSplit):
        from ..observability import CappedLogger

        self.input_format = input_format
        self.split = split
        self.counters = Counters()
        self._error_log = CappedLogger(LOG, cap=MAX_LOGGED_ERRORS)

        fields = input_format.requested_fields
        self.metadata_mode = list(fields) == [FIELDS_MAGIC]
        if self.metadata_mode:
            self.parser = None
            self._casts: Dict[str, Any] = {}
        else:
            self.parser = input_format.shared_parser()
            self._casts = {
                fid: self.parser.oracle.get_casts(fid) for fid in self.parser.requested
            }

    # -- split line iteration (LineRecordReader semantics) ------------------

    def _iter_split_lines(self) -> Iterator[bytes]:
        split = self.split
        with open(split.path, "rb") as f:
            pos = split.start
            if split.start > 0:
                # Skip the partial first line; it belongs to the previous split.
                f.seek(split.start - 1)
                prefix = f.readline()
                pos = split.start - 1 + len(prefix)
            else:
                f.seek(0)
            while pos < split.end:
                line = f.readline()
                if not line:
                    break
                pos += len(line)
                # Strip ONE newline then ONE carriage return — exactly
                # encode_blob's framing (and the regex's effective
                # view).  rstrip(b"\r\n") would eat every trailing CR,
                # so a line ending "...x\r\r\n" diverged between the
                # split reader and the feeder/blob ingest paths.
                if line.endswith(b"\n"):
                    line = line[:-1]
                if line.endswith(b"\r"):
                    line = line[:-1]
                yield line

    # -- record production --------------------------------------------------

    def __iter__(self) -> Iterator[Tuple[int, ParsedRecord]]:
        """Yield (byte-ish key, record) like (LongWritable, ParsedRecord)."""
        if self.metadata_mode:
            yield from self._iter_metadata()
            return
        batch: List[bytes] = []
        base_index = 0
        for line in self._iter_split_lines():
            batch.append(line)
            if len(batch) >= self.input_format.batch_size:
                yield from self._flush(batch, base_index)
                base_index += len(batch)
                batch = []
        if batch:
            yield from self._flush(batch, base_index)

    def _iter_metadata(self) -> Iterator[Tuple[int, ParsedRecord]]:
        """``fields`` magic: one record per possible path (RecordReader
        :233-244)."""
        for i, path in enumerate(self.input_format.list_possible_fields()):
            record = ParsedRecord()
            record.set_string(FIELDS_MAGIC, path)
            self.counters.lines_read += 1
            self.counters.good_lines += 1
            yield i, record

    def _flush(
        self, batch: List[bytes], base_index: int = 0
    ) -> Iterator[Tuple[int, ParsedRecord]]:
        from ..observability import counters as global_counters

        result = self.parser.parse_batch(batch)
        self.counters.lines_read += result.lines_read
        self.counters.bad_lines += result.bad_lines
        self.counters.good_lines += result.good_lines
        # Process-wide aggregation across all readers/splits.
        registry = global_counters()
        registry.increment("Lines read", result.lines_read)
        registry.increment("Good lines", result.good_lines)
        registry.increment("Bad lines", result.bad_lines)

        records = records_from_result(result, self.parser.requested, self._casts)
        for i, record in enumerate(records):
            if record is None:
                self._error_log.error("Parse error in line: %r", batch[i][:200])
                continue  # bad lines are skipped, not fatal
            yield base_index + i, record
