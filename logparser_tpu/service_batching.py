"""Cross-session continuous batching for the serving tier.

ROADMAP item 4's THROUGHPUT half (docs/SERVICE.md "Continuous
batching"): N concurrent sessions sending small LINES frames used to pay
N× the per-batch fixed cost — device dispatch, pad waste, one D2H
round-trip each — that ``tpu/batch.py`` amortizes so well at large batch
sizes.  This module coalesces line payloads ACROSS sessions into shared
device batches, keyed by the compiled-parser cache key (format + fields
config — and the aggregate spec, so analytics-pushdown sessions, whose
requests return aggregate frames and never enter the coalescer, can
never share a lane with row sessions even by key collision): the
LLM-serving continuous-batching trick applied to log lines,
and the device-program twin of CelerLog's route-by-format host
dispatching (PAPERS.md).

Shape:

- one :class:`_KeyBatcher` per parser cache key holds a bounded
  submission queue and a lazily-started dispatcher thread;
- session threads :meth:`BatchCoalescer.parse` → enqueue an entry and
  block on its event;
- the dispatcher claims queued entries into a formed batch (up to
  ``coalesce_max_lines``, waiting at most ``coalesce_window_ms`` for
  stragglers — and only when >1 session is live ON THIS PARSER KEY, so
  a lone client, or a format's only tenant, never pays the window),
  runs ONE device parse per formed batch, and
  scatters per-entry :meth:`~logparser_tpu.tpu.batch.BatchResult.slice`
  windows back.  Each waiting session assembles its own Arrow/IPC bytes
  from its slice, so host-side delivery still parallelizes across
  session threads;
- back-to-back formed batches run through ``parse_batch_stream`` (the
  framed payload adopted via ``parse_encoded``), so a backlog overlaps
  batch k+1's H2D upload with batch k's device work — the PR-5 staged
  edge, now engaged by serving bursts.

Robustness contract (composing with the PR-7 admission tier, never
replacing it):

- the submission queue is BOUNDED: at ``coalesce_queue_depth`` entries a
  submit raises :class:`CoalesceQueueFull` and the service sheds a
  structured ``BUSY {"reason":"coalesce_queue"}`` — coalescing must not
  reintroduce the unbounded queue admission control exists to prevent;
- the queue occupancy feeds the process-wide
  :func:`logparser_tpu.feeder.queue_backpressure` signal (the coalescer
  registers itself as a backpressure source), so the per-request
  admission leg sheds BEFORE the queue hard-fills;
- a request deadline expires a WAITING entry without poisoning the
  shared batch: the waiter (or the dispatcher, when it reaches an
  already-expired entry) cancels it under the batcher lock before batch
  formation and the session answers a structured ``DEADLINE`` frame; an
  entry already claimed into an in-flight batch delivers normally and
  the late result is discarded by the session's deadline machinery;
- an ABANDONED in-flight batch (every claimed entry's deadline expired
  while the shared parse is still running — a wedged or pathologically
  slow parse) RECYCLES the lane (round 15): the dispatcher epoch is
  bumped, a fresh dispatcher takes over the submission queue, and the
  stale dispatcher delivers its doomed batch in the background and
  exits — one wedged parse no longer head-of-line-blocks every session
  on that format key (``service_coalesce_lane_recycles_total``).  The
  abandoned requests' worker threads still hold their in-flight slots
  until the wedged parse truly stops, so the admission backpressure a
  wedge is supposed to exert is preserved;
- drain-safety: queued entries belong to admitted sessions, so a
  graceful drain's session wait inherently waits for the coalescer to
  finish them; :meth:`BatchCoalescer.shutdown` runs after the session
  join and fails any orphaned entry loudly instead of hanging it.

Parity invariant (the reason this is safe to default ON): the scattered
per-session results are BYTE-identical to what solo parsing would have
produced — guaranteed by ``BatchResult.slice``'s per-row independence
contract and locked by the cross-session parity suite in
tests/test_service.py, the golden protocol vectors, and
tools/coalesce_smoke.py.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from .observability import log_warning_once, metrics

LOG = logging.getLogger(__name__)

# Histogram bucket bounds (docs/OBSERVABILITY.md): occupancy is a 0-1
# fill fraction of the configured batch geometry; wait is the queue time
# an entry spent before claim; sessions/batch is the coalescing win.
OCCUPANCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
WAIT_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 1.0, 5.0)
SESSIONS_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0, 64.0)

# Dispatcher threads exit after this long idle; the next submit restarts
# one (a long-lived sidecar serving many historical configs must not
# keep a thread per cold parser key).
_IDLE_EXIT_S = 30.0
# Batcher registry bound: beyond it, idle (empty-queue) batchers are
# evicted LRU — mirrors the parser cache's own LRU bound.
_MAX_BATCHERS = 64


class CoalesceQueueFull(Exception):
    """The shared submission queue is at capacity: the request must SHED
    (structured ``BUSY {"reason":"coalesce_queue"}``) instead of queueing
    without bound behind the device — the admission contract
    (docs/SERVICE.md) extended to the coalescer's own queue."""


class CoalesceDeadline(Exception):
    """The request deadline expired while the entry was still QUEUED.
    The entry was cancelled BEFORE batch formation — the shared batch is
    not poisoned — and the session answers a structured ``DEADLINE``
    frame exactly as a solo slow parse would."""


class CoalesceShutdown(Exception):
    """The service shut down with this entry still queued (only possible
    for a session force-closed past the drain deadline — a graceful
    drain finishes queued entries before the coalescer stops)."""


class _Entry:
    """One session's queued request: the payload, its line count, and
    the rendezvous the session thread blocks on.  State transitions are
    guarded by the owning batcher's lock: PENDING -> CLAIMED (dispatcher
    took it into a formed batch) or PENDING -> CANCELLED (deadline /
    shutdown); CLAIMED entries always get ``result`` or ``error``."""

    __slots__ = ("blob", "count", "enq_t", "deadline_t", "max_wait_t",
                 "event", "state", "result", "error", "abandoned",
                 "trace_ctx")

    PENDING, CLAIMED, CANCELLED = range(3)

    def __init__(self, blob: bytes, count: int,
                 deadline_t: Optional[float],
                 max_wait_t: Optional[float] = None,
                 trace_ctx: Any = None):
        self.blob = blob
        self.count = count
        self.enq_t = time.monotonic()
        self.deadline_t = deadline_t
        # The submitting request's TraceContext (or None): the formed
        # batch's span LINKS to every member's context — the fan-in IS
        # the signal (N sessions provably shared one device batch).
        # Never part of batching/parsing decisions.
        self.trace_ctx = trace_ctx
        # Client batching hint (PROTOCOL.md "coalesce_wait_ms"): the
        # absolute time by which a forming batch holding this entry must
        # stop waiting for stragglers — a latency-critical session caps
        # the straggler window it is willing to pay, without changing
        # parsing, sharing, shedding, or result bytes.
        self.max_wait_t = max_wait_t
        self.event = threading.Event()
        self.state = _Entry.PENDING
        self.result: Any = None
        self.error: Optional[BaseException] = None
        # CLAIMED entry whose waiter's deadline expired mid-flight: the
        # session already answered DEADLINE and will discard the late
        # result.  When EVERY in-flight entry is abandoned the lane
        # recycles (head-of-line-blocking fix, round 15).
        self.abandoned = False


class _FormedBatch:
    """Entries claimed into one shared device batch, in claim order.
    Row offsets are the running line counts — entry k's result is rows
    ``[offset_k, offset_k + count_k)`` of the combined parse."""

    __slots__ = ("entries", "total", "span")

    def __init__(self, entries: List[_Entry]):
        self.entries = entries
        self.total = sum(e.count for e in entries)
        # Live coalesce_batch trace span (or None): opened at formation,
        # closed after scatter — the stream path's lifetime crosses the
        # generator frame, so it rides the batch, not a with-block.
        self.span = None

    def blob(self) -> bytes:
        return b"\n".join(e.blob for e in self.entries)

    def encoded(self):
        """The combined payload framed exactly as ``parse_blob`` frames
        it (``native.encode_blob``), wrapped as a feeder
        :class:`~logparser_tpu.feeder.worker.EncodedBatch` so
        ``parse_batch_stream``/``parse_encoded`` adopt it without a
        re-scan — and so back-to-back formed batches ride the staged-H2D
        double buffer."""
        from .feeder.worker import EncodedBatch
        from .native import encode_blob

        blob = self.blob()
        buf, lengths, overflow = encode_blob(blob)
        return EncodedBatch(
            shard=0, index=0, payload=blob, buf=buf, lengths=lengths,
            overflow=list(overflow), n_lines=self.total,
        )


def _begin_batch_span(fb: _FormedBatch) -> Any:
    """Open the ONE shared-batch span (docs/OBSERVABILITY.md "Tracing"):
    parented on the first sampled member's context, span-LINKED to every
    member — N sessions provably share this device batch.  Pushed as the
    stage-attribution target so PIPELINE_STAGES become its children.
    Returns None (and touches nothing) when no member is sampled."""
    head = None
    for e in fb.entries:
        if e.trace_ctx is not None and getattr(e.trace_ctx, "sampled", False):
            head = e.trace_ctx
            break
    if head is None:
        return None
    from .tracing import child_span, push_batch_span

    span = child_span(
        "coalesce_batch", head,
        attrs={"sessions": len(fb.entries), "lines": fb.total},
    )
    for e in fb.entries:
        if e.trace_ctx is not None:
            span.add_link(e.trace_ctx)
    push_batch_span(span)
    return span


def _end_batch_span(span: Any) -> None:
    if span is None:
        return
    from .tracing import pop_batch_span

    pop_batch_span(span)
    span.end()


class _KeyBatcher:
    """The per-parser-cache-key coalescing lane: bounded submission
    queue + one dispatcher thread, started lazily and exiting when
    idle."""

    def __init__(self, co: "BatchCoalescer", key: Any, parser: Any,
                 seq: int):
        self.co = co
        self.key = key
        self.parser = parser
        self.seq = seq
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.queue: "deque[_Entry]" = deque()
        self.thread: Optional[threading.Thread] = None
        self.stopped = False
        self.last_used = time.monotonic()
        # Dispatcher ownership epoch: bumped by a lane recycle (every
        # in-flight entry abandoned).  A dispatcher whose captured epoch
        # is stale delivers its already-claimed batches and exits — it
        # must never claim fresh queue entries again.
        self.epoch = 0
        # Entries CLAIMED but not yet resolved, in claim order (the
        # recycle trigger reads it; guarded by ``lock``).
        self.inflight: List[_Entry] = []

    # -- submit side (session threads) ---------------------------------

    def submit(self, blob: bytes, count: int,
               deadline_s: Optional[float],
               max_wait_s: Optional[float] = None,
               trace_ctx: Any = None) -> _Entry:
        now = time.monotonic()
        entry = _Entry(blob, count,
                       now + deadline_s if deadline_s else None,
                       now + max_wait_s if max_wait_s is not None
                       else None,
                       trace_ctx=trace_ctx)
        with self.lock:
            if self.stopped:
                raise CoalesceShutdown("service is shutting down")
            if len(self.queue) >= self.co.queue_depth:
                raise CoalesceQueueFull(
                    f"coalesce queue at capacity "
                    f"({self.co.queue_depth} entries)"
                )
            self.queue.append(entry)
            self.last_used = now
            self._ensure_thread_locked()
            self.cond.notify_all()
        metrics().gauge_add("service_coalesce_queue_depth", 1)
        return entry

    def wait(self, entry: _Entry, deadline_s: Optional[float]):
        """Block the session thread until the entry's result/error.  On
        deadline: cancel if still PENDING (the batch is not poisoned);
        if already CLAIMED the batch is in flight — mark the entry
        ABANDONED (recycling the lane once the whole in-flight batch
        is), then wait it out: the session's own deadline machinery
        answers the client and discards this late result, and this
        worker thread keeps its in-flight slot until the parse truly
        stops (wedge -> backpressure, docs/SERVICE.md)."""
        if not entry.event.wait(deadline_s):
            with self.lock:
                if entry.state == _Entry.PENDING:
                    entry.state = _Entry.CANCELLED
                    entry.error = CoalesceDeadline(
                        "request deadline expired in the coalesce queue"
                    )
                    metrics().increment("service_coalesce_expired_total")
                    metrics().gauge_add("service_coalesce_queue_depth", -1)
                    raise entry.error
            self._note_abandoned(entry)
            entry.event.wait()
        if entry.error is not None:
            raise entry.error
        return entry.result

    # -- dispatch side --------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self.thread is None or not self.thread.is_alive():
            self.thread = threading.Thread(
                target=self._run, args=(self.epoch,),
                name=f"svc-coalesce-{self.seq}", daemon=True,
            )
            self.thread.start()

    def _note_abandoned(self, entry: _Entry) -> None:
        """A waiter's deadline expired on a CLAIMED entry.  When that
        leaves the ENTIRE in-flight population abandoned, nobody is
        waiting for the batch the dispatcher is stuck on — recycle the
        lane: bump the epoch (the stale dispatcher delivers its doomed
        batches and exits without ever touching the queue again) and
        hand the submission queue to a fresh dispatcher, so one wedged
        parse cannot stall every session on this format key."""
        recycled = False
        with self.lock:
            entry.abandoned = True
            if self.stopped or entry.event.is_set():
                return
            if not self.inflight or not all(
                e.abandoned or e.event.is_set() for e in self.inflight
            ):
                return
            self.epoch += 1
            self.thread = None
            if self.queue:
                self._ensure_thread_locked()
            self.cond.notify_all()
            recycled = True
        if recycled:
            metrics().increment("service_coalesce_lane_recycles_total")
            log_warning_once(
                LOG,
                "coalesce lane recycled around an abandoned in-flight "
                "batch (every waiter's deadline expired; the wedged "
                "parse finishes in the background)",
            )

    def _run(self, my_epoch: int) -> None:
        try:
            while True:
                with self.lock:
                    if self.epoch != my_epoch:
                        return  # recycled: a fresh dispatcher owns the queue
                    while not self.queue and not self.stopped:
                        if not self.cond.wait(timeout=_IDLE_EXIT_S):
                            if not self.queue and not self.stopped \
                                    and self.epoch == my_epoch:
                                # Idle exit: a later submit restarts one.
                                self.thread = None
                                return
                        if self.epoch != my_epoch:
                            return
                    if self.stopped and not self.queue:
                        return
                self._burst(my_epoch)
        except Exception as e:  # noqa: BLE001 — a lane must fail loudly
            # A dispatcher crash outside _burst's per-batch handling:
            # fail every queued entry (waiters get the error frame, not
            # a hang) and clear the thread slot so the lane can recover.
            log_warning_once(
                LOG,
                "coalesce dispatcher failed; queued entries answered "
                "with the error and the lane restarted "
                "(details at DEBUG)",
            )
            LOG.debug("coalesce dispatcher fault on key %r", self.key,
                      exc_info=True)
            with self.lock:
                if self.epoch != my_epoch:
                    # Recycled mid-crash: the queue belongs to the new
                    # dispatcher — only this incarnation's own claimed
                    # entries (already resolved by _burst's handlers)
                    # were affected.
                    return
                drained = list(self.queue)
                self.queue.clear()
                self.thread = None
                # State flips under the lock (the waiter-cancel path
                # races this); each PENDING->CANCELLED flip owns one
                # gauge decrement.
                cancelled = 0
                for entry in drained:
                    if entry.state == _Entry.PENDING:
                        entry.state = _Entry.CANCELLED
                        cancelled += 1
            if cancelled:
                metrics().gauge_add("service_coalesce_queue_depth",
                                    -cancelled)
            for entry in drained:
                self._finish(entry, error=e)

    def _claim_locked(self, claimed: List[_Entry], now: float) -> int:
        """Move eligible queue entries into ``claimed`` (respecting the
        line budget); expire already-dead ones.  Returns claimed line
        total.  Caller holds the lock."""
        reg = metrics()
        total = sum(e.count for e in claimed)
        while self.queue and total < self.co.max_lines:
            e = self.queue[0]
            if e.state == _Entry.CANCELLED:
                self.queue.popleft()
                continue
            if e.deadline_t is not None and now >= e.deadline_t:
                # Expired while queued: drop BEFORE batch formation so
                # the shared batch never carries a dead entry.
                self.queue.popleft()
                e.state = _Entry.CANCELLED
                e.error = CoalesceDeadline(
                    "request deadline expired in the coalesce queue"
                )
                reg.increment("service_coalesce_expired_total")
                reg.gauge_add("service_coalesce_queue_depth", -1)
                e.event.set()
                continue
            if claimed and total + e.count > self.co.max_lines:
                break  # keep the batch inside the configured geometry
            self.queue.popleft()
            e.state = _Entry.CLAIMED
            self.inflight.append(e)
            claimed.append(e)
            total += e.count
            reg.observe("service_coalesce_wait_seconds", now - e.enq_t,
                        buckets=WAIT_BUCKETS)
            reg.gauge_add("service_coalesce_queue_depth", -1)
        return total

    def _form(self, my_epoch: int) -> Optional[_FormedBatch]:
        """Form the next batch from the queue: claim what is there, then
        wait up to the coalesce window for stragglers (only when more
        than one session is live — a lone client must not pay the
        window, and an already-full batch never waits).  Inside a burst
        the window wait OVERLAPS the in-flight batch's async device
        work — dispatch is asynchronous, so filling batch k+1 while
        batch k computes costs nothing and roughly doubles occupancy
        (measured 2.2 -> 3.9 sessions/batch at 8 clients on the 2-core
        container, 1.37x -> 2.1x goodput over per-session dispatch).
        None (empty queue after the wait, or a stale dispatcher epoch —
        the lane was recycled) ends the burst."""
        claimed: List[_Entry] = []
        with self.lock:
            if self.epoch != my_epoch:
                return None
            total = self._claim_locked(claimed, time.monotonic())
            if (
                claimed and not self.stopped
                and self.co.window_s > 0.0
                and total < self.co.max_lines
                and self.co.should_wait(self.key)
            ):
                end = self._window_end(
                    claimed, time.monotonic() + self.co.window_s
                )
                while total < self.co.max_lines:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    self.cond.wait(remaining)
                    if self.stopped or self.epoch != my_epoch:
                        break
                    total = self._claim_locked(claimed, time.monotonic())
                    # A newly claimed entry may carry a TIGHTER
                    # per-session wait cap (coalesce_wait_ms): the
                    # formation window shrinks to the strictest member.
                    end = min(end, self._window_end(claimed, end))
        if not claimed:
            return None
        return _FormedBatch(claimed)

    @staticmethod
    def _window_end(claimed: List[_Entry], default_end: float) -> float:
        """When the straggler wait over ``claimed`` must stop: the
        configured window end, clamped by every member's own
        ``coalesce_wait_ms`` cap (the strictest session in the batch
        decides — a 0 ms hint dispatches the batch immediately)."""
        end = default_end
        for e in claimed:
            if e.max_wait_t is not None and e.max_wait_t < end:
                end = e.max_wait_t
        return end

    def _burst(self, my_epoch: int) -> None:
        """Drain the backlog as one stream of formed batches: ONE device
        parse per formed batch, back-to-back batches overlapping upload
        with compute via ``parse_batch_stream``'s staged-H2D edge.
        Parser doubles without the streaming API take a plain
        ``parse_blob`` per formed batch."""
        parser = self.parser
        if not (hasattr(parser, "parse_batch_stream")
                and hasattr(parser, "parse_encoded")):
            fb = self._form(my_epoch)
            while fb is not None:
                fb.span = _begin_batch_span(fb)
                try:
                    self._scatter(fb, parser.parse_blob(
                        fb.blob(), emit_views=False))
                except Exception as e:  # noqa: BLE001 — relayed per entry
                    self._fail(fb, e)
                finally:
                    _end_batch_span(fb.span)
                fb = self._form(my_epoch)
            return

        formed: "deque[_FormedBatch]" = deque()

        def gen():
            while True:
                fb = self._form(my_epoch)
                if fb is None:
                    return
                fb.span = _begin_batch_span(fb)
                formed.append(fb)
                yield fb.encoded()

        try:
            for result in parser.parse_batch_stream(gen(),
                                                    emit_views=False):
                fb = formed.popleft()
                try:
                    self._scatter(fb, result)
                except Exception as e:  # noqa: BLE001 — relayed per entry
                    # A partial scatter (e.g. a slice fault mid-batch)
                    # must still resolve EVERY entry of the popped batch
                    # — _finish is first-write-wins, so already-delivered
                    # entries keep their results and only the unresolved
                    # tail gets the error.  An unresolved entry would
                    # hang its session thread and leak its in-flight
                    # slot forever.
                    self._fail(fb, e)
                finally:
                    _end_batch_span(fb.span)
        except Exception as e:  # noqa: BLE001 — relayed per entry
            # A mid-stream failure costs the formed-but-undelivered
            # batches their requests (each answered with the error
            # frame); entries still queued are untouched and retry on
            # the restarted lane.
            while formed:
                fb = formed.popleft()
                _end_batch_span(fb.span)
                self._fail(fb, e)

    def _finish(self, entry: _Entry, result: Any = None,
                error: Optional[BaseException] = None) -> None:
        # First write wins: a batch-level _fail after a partial scatter
        # must not overwrite an already-delivered entry's result with
        # the error (the waiter may already be reading it).
        if entry.event.is_set():
            return
        entry.result = result
        entry.error = error
        with self.lock:
            # Off the recycle trigger's ledger BEFORE the event flips:
            # a resolved entry must never count toward "the whole
            # in-flight batch is abandoned".
            try:
                self.inflight.remove(entry)
            except ValueError:
                pass
        entry.event.set()

    def _fail(self, fb: _FormedBatch, error: BaseException) -> None:
        for entry in fb.entries:
            self._finish(entry, error=error)

    def _scatter(self, fb: _FormedBatch, result: Any) -> None:
        """Hand each claimed entry its row window of the shared result.
        Session threads do their own Arrow assembly from the slice, so
        delivery stays parallel across sessions."""
        reg = metrics()
        reg.increment("service_coalesce_batches_total")
        reg.increment("service_coalesced_requests_total", len(fb.entries))
        reg.observe("service_coalesced_sessions_per_batch",
                    float(len(fb.entries)), buckets=SESSIONS_BUCKETS)
        reg.observe("service_coalesce_batch_occupancy",
                    fb.total / max(1, self.co.max_lines),
                    buckets=OCCUPANCY_BUCKETS)
        if len(fb.entries) == 1:
            self._finish(fb.entries[0], result=result)
            return
        if not hasattr(result, "slice"):
            # Parser double / exotic result without the slicing contract:
            # re-parse each payload solo — slower, trivially
            # parity-correct (only reachable with injected test parsers).
            for entry in fb.entries:
                try:
                    self._finish(entry, result=self.parser.parse_blob(
                        entry.blob, emit_views=False))
                except Exception as e:  # noqa: BLE001
                    self._finish(entry, error=e)
            return
        row = 0
        for entry in fb.entries:
            self._finish(entry, result=result.slice(row, row + entry.count))
            row += entry.count

    # -- teardown -------------------------------------------------------

    def stop(self) -> "Optional[threading.Thread]":
        """Flag the lane stopped, fail queued entries, return the
        dispatcher thread (if any) for the caller to join."""
        with self.lock:
            self.stopped = True
            drained = []
            for entry in self.queue:
                # State flips under the lock (the waiter-cancel path
                # races this); each flip owns one gauge decrement.
                if entry.state == _Entry.PENDING:
                    entry.state = _Entry.CANCELLED
                    drained.append(entry)
            self.queue.clear()
            self.cond.notify_all()
            thread = self.thread
        if drained:
            metrics().gauge_add("service_coalesce_queue_depth",
                                -len(drained))
        for entry in drained:
            self._finish(entry, error=CoalesceShutdown(
                "service shut down with the request still queued"
            ))
        return thread


class BatchCoalescer:
    """The service-wide coalescer: one :class:`_KeyBatcher` per parser
    cache key, an aggregate :meth:`backpressure` signal registered with
    the feeder fabric's process-wide
    :func:`~logparser_tpu.feeder.queue_backpressure`, and a bounded
    batcher registry (idle lanes evict LRU)."""

    def __init__(self, *, window_s: float, max_lines: int,
                 queue_depth: int,
                 live_sessions_fn: Optional[Callable[[Any], int]] = None,
                 max_batchers: int = _MAX_BATCHERS):
        self.window_s = max(0.0, float(window_s))
        self.max_lines = max(1, int(max_lines))
        self.queue_depth = max(1, int(queue_depth))
        self._live_sessions_fn = live_sessions_fn
        self._max_batchers = max(1, int(max_batchers))
        self._lock = threading.Lock()
        self._batchers: "OrderedDict[Any, _KeyBatcher]" = OrderedDict()
        self._seq = 0
        self._closed = False
        from .feeder import register_backpressure_source

        register_backpressure_source(self)

    # -- the request path ----------------------------------------------

    def parse(self, key: Any, parser: Any, blob: bytes, count: int,
              deadline_s: Optional[float] = None,
              max_wait_s: Optional[float] = None,
              trace_ctx: Any = None):
        """Coalesce one request's payload into the key's shared batch
        stream; returns the session's own
        :class:`~logparser_tpu.tpu.batch.BatchResult` window (byte-
        identical to a solo parse of ``blob``).  ``max_wait_s`` is the
        session's ``coalesce_wait_ms`` hint: a cap on the straggler
        window any batch holding this request may pay (0 = dispatch as
        soon as claimed); parsing, queue bounds, and shed behavior are
        untouched.  Raises :class:`CoalesceQueueFull` (shed),
        :class:`CoalesceDeadline` (expired while queued),
        :class:`CoalesceShutdown`, or whatever the shared parse
        raised."""
        for _ in range(2):
            batcher = self._batcher(key, parser)
            try:
                entry = batcher.submit(blob, count, deadline_s,
                                       max_wait_s, trace_ctx=trace_ctx)
            except CoalesceShutdown:
                if self._closed:
                    raise
                # An LRU-evicted idle lane raced this submit: the key is
                # already out of the registry, so the next _batcher()
                # call builds a fresh one.
                continue
            return batcher.wait(entry, deadline_s)
        raise CoalesceShutdown("service is shutting down")

    def _batcher(self, key: Any, parser: Any) -> _KeyBatcher:
        with self._lock:
            if self._closed:
                raise CoalesceShutdown("service is shutting down")
            b = self._batchers.get(key)
            if b is None:
                self._seq += 1
                b = _KeyBatcher(self, key, parser, self._seq)
                self._batchers[key] = b
                self._evict_locked()
            else:
                # A recompiled parser for the same config (cache evict +
                # rebuild) produces identical results: adopt the fresh
                # object so the lane never pins a stale executor.
                b.parser = parser
                self._batchers.move_to_end(key)
            return b

    def _evict_locked(self) -> None:
        if len(self._batchers) <= self._max_batchers:
            return
        for key, b in list(self._batchers.items()):
            if len(self._batchers) <= self._max_batchers:
                return
            with b.lock:
                idle = not b.queue
                if idle:
                    b.stopped = True
                    b.cond.notify_all()
            if idle:
                del self._batchers[key]

    # -- signals --------------------------------------------------------

    def should_wait(self, key: Any) -> bool:
        """Whether the coalesce window is worth paying for ``key``'s
        lane: only when more than one session is live ON THAT PARSER
        KEY — a lone client (or the only tenant of a format, however
        busy the other formats are) has nobody to coalesce with, so
        waiting would be pure added latency."""
        fn = self._live_sessions_fn
        if fn is None:
            return True
        try:
            return fn(key) > 1
        except Exception:  # noqa: BLE001 — an unknown count must not stall
            return True

    def backpressure(self) -> float:
        """Worst per-key queue occupancy as a 0-1 fraction of the
        bounded depth — the coalescer's contribution to the process-wide
        :func:`~logparser_tpu.feeder.queue_backpressure` aggregate the
        admission tier sheds on (docs/SERVICE.md)."""
        if self._closed:
            return 0.0
        worst = 0.0
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            worst = max(worst, len(b.queue) / float(self.queue_depth))
        return min(1.0, worst)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "batchers": len(self._batchers),
                "queued_entries": sum(
                    len(b.queue) for b in self._batchers.values()
                ),
            }

    # -- teardown -------------------------------------------------------

    def shutdown(self, join_timeout_s: float = 5.0) -> None:
        """Stop every lane: fail still-queued entries loudly (see
        :class:`CoalesceShutdown` — a graceful drain finishes queued
        entries BEFORE this runs, because they belong to admitted
        sessions the drain waits for) and join dispatcher threads under
        one shared budget."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = list(self._batchers.values())
            self._batchers.clear()
        from .feeder import deregister_backpressure_source

        deregister_backpressure_source(self)
        threads = [t for t in (b.stop() for b in batchers) if t is not None]
        end = time.monotonic() + max(0.0, join_timeout_s)
        for t in threads:
            t.join(timeout=max(0.0, end - time.monotonic()))
            if t.is_alive():
                from .observability import note_teardown

                note_teardown(
                    LOG, "service_teardown_errors_total", "coalesce_join",
                    f"coalesce dispatcher {t.name} outlived its join",
                )
