"""Sidecar parse service: any-host interop over Arrow IPC.

SURVEY §7 step 5: "Java/any-host interop over Arrow IPC; sidecar service
mode".  The reference embeds the parser in-process in each engine (Hadoop,
Pig, Hive, ...); the TPU-native equivalent offers the same capability to
non-Python hosts by running the batch parser behind a socket: a JVM/Go/C++
data engine ships raw loglines to the sidecar and gets typed Arrow columns
back, so one TPU-attached process serves many engine workers.

Wire protocol (deliberately trivial to implement from any language):

    frame     := u32 big-endian length, then `length` payload bytes
    session   := CONFIG frame, then any number of
                 [LINES frame -> ARROW frame [-> STATS frame]]
    CONFIG    := JSON {"log_format": str, "fields": [str, ...],
                       "timestamp_format": str|null,
                       "assembly_workers": int|null (optional; host-side
                       Arrow assembly parallelism, default auto),
                       "feeder_workers": int|null (optional; >= 2 = frame
                       large LINES payloads through the sharded feeder
                       fabric — N threads frame disjoint byte-range shards
                       in parallel; the ARROW frame is unchanged in shape
                       and content, docs/FEEDER.md.  The fabric degrades,
                       never drops: a feeder failure re-parses the request
                       inline and demotes the session to inline parsing
                       for its remaining frames,
                       service_feeder_demotions_total),
                       "stats": bool (optional; true = one STATS JSON frame
                       after each ARROW frame — v1 sessions that omit the
                       key get byte-identical v1 behavior)}
    LINES     := u32 big-endian line count, then the loglines joined by '\n'
                 (UTF-8).  Loglines cannot contain '\n' — they are lines.
                 count=0 means an empty batch (an empty ARROW table comes
                 back); an empty logline is a present-but-empty row.
    ARROW     := one Arrow IPC stream (schema + one record batch) with the
                 requested columns plus the `__valid__` validity column
    STATS     := UTF-8 JSON telemetry frame (docs/PROTOCOL.md "stats" key):
                 per-request timing/sizes + process-cumulative stage
                 breakdown from the metrics registry
    error     := in place of an ARROW frame: 0xFFFFFFFF marker frame followed
                 by one frame of UTF-8 error text
    length 0  := end of session (client side); server closes the connection

Compiled parsers are cached per config, so successive sessions with the same
LogFormat skip recompilation (the service-side analogue of the reference's
"compile the Pattern only once", TokenFormatDissector.java:209-210).

Robustness contract (round 12, docs/SERVICE.md — the serving twin of the
feeder's "degrade, never drop" fault model):

- **Admission control & load shedding.**  Concurrent sessions are bounded
  by ``max_sessions`` and concurrently-parsing requests by
  ``max_inflight``; the per-request check is additionally wired to the
  feeder fabric's queue-backpressure signal
  (:func:`logparser_tpu.feeder.queue_backpressure`).  Over budget, the
  server answers with a STRUCTURED ``BUSY`` error frame carrying a
  retry-after hint — never a TCP reset — and counts the shed in
  ``service_shed_total{reason}``.
- **Deadlines everywhere.**  Per-frame socket read timeouts, a per-session
  idle timeout, and an optional per-request parse deadline
  (``request_deadline_s``): an expired request yields a ``DEADLINE`` error
  frame and the session SURVIVES.
- **Input hardening.**  Frame-length ceilings and CONFIG/LINES payload
  caps are enforced BEFORE allocation: a hostile 4 GiB length prefix or a
  junk CONFIG costs one error frame, not an OOM.
- **Graceful drain.**  ``shutdown(drain=True)`` (SIGTERM under the CLI)
  stops accepting, flips ``/readyz`` to draining so orchestrators stop
  routing, lets admitted sessions finish under ``drain_deadline_s``, then
  escalates force-close -> join — leaked threads are warned once and
  counted (``service_teardown_errors_total{site}``), never silent.

Throughput contract (round 14, docs/SERVICE.md "Continuous batching"):
concurrent sessions sharing a parser config COALESCE into shared device
batches (:mod:`logparser_tpu.service_batching`): per-batch fixed costs
(dispatch, pad waste, D2H round-trip) amortize across sessions, each
session scatters back its exact row window — BYTE-identical to solo
parsing, so nothing changes on the wire — and the coalescer's bounded
queue composes with the admission tier above (full queue = structured
``BUSY{coalesce_queue}``; queue occupancy feeds ``queue_backpressure()``;
request deadlines expire queued entries without poisoning shared
batches).  Knobs: ``coalesce`` / ``coalesce_window_ms`` /
``coalesce_max_lines`` / ``coalesce_queue_depth``.

Observability (docs/OBSERVABILITY.md): the service renders the process-wide
metrics registry as a Prometheus ``/metrics`` HTTP endpoint
(``metrics_port=``, or LOGPARSER_TPU_METRICS_PORT for the CLI) plus
``/healthz`` (liveness) and ``/readyz`` (readiness; 503 while draining),
and can log a periodic one-line stats summary (``stats_interval=`` /
LOGPARSER_TPU_STATS_INTERVAL).  ``python -m logparser_tpu.service`` runs
the sidecar standalone with all of it wired up.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import queue
import random
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from .observability import (
    log_version_banner_once,
    log_warning_once,
    metrics,
    note_teardown,
    suppressed_warning_counts,
)
from .tracing import (
    child_span,
    flight_event,
    flightz_payload,
    parse_traceparent,
    tracez_payload,
)

LOG = logging.getLogger(__name__)

_ERROR_MARKER = 0xFFFFFFFF
_MAX_FRAME = 1 << 30  # 1 GiB absolute frame ceiling (protocol v1)
# Sharded-feeder engagement floor: below this many lines a LINES frame is
# parsed inline — splitting pays for itself only when the framing work
# dwarfs the per-shard setup (docs/FEEDER.md "worker sizing").
_FEEDER_MIN_LINES = 4096
# Bounds for the courtesy read-to-EOF after a terminal error response: the
# peer may still be mid-send, and closing with unread bytes in the receive
# buffer turns into an RST that can discard the very frame just written.
_LINGER_DRAIN_S = 1.0
_LINGER_DRAIN_BYTES = 4 << 20


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    """One length-prefixed frame; None on clean EOF or length-0 frame.
    Error responses raise the CLASSIFIED service error
    (:func:`classify_service_error`): plain :class:`ParseServiceError`,
    or its :class:`ServiceBusyError` / :class:`ServiceDeadlineError`
    structured subclasses."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length == 0:
        return None
    if length == _ERROR_MARKER:
        payload = read_frame(sock)
        raise classify_service_error(
            (payload or b"(no error text)").decode("utf-8", errors="replace")
        )
    if length > _MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {_MAX_FRAME}")
    return _read_exact(sock, length)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls: no header+payload concatenation copy (Arrow responses
    # can be large).
    sock.sendall(struct.pack(">I", len(payload)))
    sock.sendall(payload)


def write_error(sock: socket.socket, message: str) -> None:
    sock.sendall(struct.pack(">I", _ERROR_MARKER))
    write_frame(sock, message.encode("utf-8"))


class ParseServiceError(RuntimeError):
    """Server-side failure relayed to the client."""


class ServiceClosedError(ParseServiceError):
    """The server closed the connection where a response frame was due —
    the one outcome the shedding/deadline machinery exists to prevent
    (an orderly server always answers with a structured frame first)."""


class ServiceBusyError(ParseServiceError):
    """Structured ``BUSY`` overload response (docs/PROTOCOL.md "Overload
    responses"): the request (reason ``inflight``/``backpressure``) or
    the whole connection (reason ``sessions``/``draining``/
    ``sidecar_failover``/``tenant_quota``) was SHED.  ``retry_after_s``
    is the server's backoff hint; ``structured`` is False only for a
    BUSY-prefixed frame whose JSON failed to parse."""

    def __init__(self, message: str, reason: str = "busy",
                 retry_after_s: float = 0.0, structured: bool = True):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.structured = structured


class ServiceUnavailableError(ParseServiceError):
    """The client exhausted its ``max_redirect_retries`` budget on
    connection-level sheds (``draining``/``sidecar_failover``/
    ``sessions``): every reconnect landed on a server that refused the
    whole connection again — the fleet (or the lone server) is
    UNAVAILABLE and the caller should fail fast, not keep spinning
    through reconnect/backoff cycles (docs/SERVICE.md "Client retry
    contract")."""


#: BUSY reasons that shed the whole CONNECTION (the server closes the
#: socket by contract): the client must reconnect before retrying, and
#: each one counts against ``max_redirect_retries``.  The single
#: source of truth — the front tier and loadgen reuse it.
#: ``tenant_quota`` is the SESSION-level tenant shed; the front's
#: request-level tenant shed is the distinct reason ``tenant_inflight``
#: (session survives, resend on the same connection) precisely so
#: clients never have to guess which kind they got.
RECONNECT_BUSY_REASONS = ("sessions", "draining", "sidecar_failover",
                          "tenant_quota")


class ServiceDeadlineError(ParseServiceError):
    """Structured ``DEADLINE`` response: the per-request parse deadline
    expired server-side.  The session survives — the next LINES frame is
    processed normally."""

    def __init__(self, message: str, deadline_s: float = 0.0):
        super().__init__(message)
        self.deadline_s = deadline_s


def busy_error_text(reason: str, retry_after_s: float) -> str:
    """The structured BUSY error-frame text (docs/PROTOCOL.md): the code
    word, one space, then a JSON object — trivially parseable from any
    client language, still readable as plain text by a v1 client."""
    return "BUSY " + json.dumps(
        {"reason": reason, "retry_after_ms": int(retry_after_s * 1000.0)},
        sort_keys=True, separators=(",", ":"),
    )


def deadline_error_text(deadline_s: float) -> str:
    """The structured DEADLINE error-frame text (docs/PROTOCOL.md)."""
    return "DEADLINE " + json.dumps(
        {"deadline_ms": int(deadline_s * 1000.0)},
        sort_keys=True, separators=(",", ":"),
    )


def classify_service_error(text: str) -> ParseServiceError:
    """Map error-frame text to the richest matching exception: the
    ``BUSY ``/``DEADLINE `` structured prefixes (round 12) become their
    typed subclasses, anything else the plain :class:`ParseServiceError`.
    A structured prefix with junk JSON still classifies (the code word is
    the contract; the JSON is the detail) but is flagged unstructured."""
    if text.startswith("BUSY"):
        try:
            detail = json.loads(text[4:].strip() or "{}")
            if not isinstance(detail, dict):
                raise TypeError("detail is not an object")
            return ServiceBusyError(
                text,
                reason=str(detail.get("reason", "busy")),
                retry_after_s=float(detail.get("retry_after_ms", 0)) / 1000.0,
            )
        except (ValueError, TypeError):
            return ServiceBusyError(text, structured=False)
    if text.startswith("DEADLINE"):
        try:
            detail = json.loads(text[8:].strip() or "{}")
            if not isinstance(detail, dict):
                raise TypeError("detail is not an object")
            return ServiceDeadlineError(
                text,
                deadline_s=float(detail.get("deadline_ms", 0)) / 1000.0,
            )
        except (ValueError, TypeError):
            return ServiceDeadlineError(text)
    return ParseServiceError(text)


# ---------------------------------------------------------------------------
# server-side frame reading: deadlines + pre-allocation ceilings
# ---------------------------------------------------------------------------


class _SessionTimeout(Exception):
    """A server-side read deadline fired: ``kind`` is ``"idle"`` (no
    frame started inside the idle window) or ``"frame"`` (a started
    frame stalled mid-transfer — unresyncable, the session closes)."""

    def __init__(self, kind: str):
        super().__init__(kind)
        self.kind = kind


class _FrameTooLarge(Exception):
    """A frame announced a length over a ceiling BEFORE any allocation.
    ``fatal=True``: over the absolute frame cap — the payload was not
    consumed and the session cannot resync (error frame, then close).
    ``fatal=False``: over a payload cap — the payload was READ AND
    DISCARDED in bounded chunks, so the session survives to the next
    frame."""

    def __init__(self, length: int, cap: int, fatal: bool):
        super().__init__(f"frame of {length} bytes exceeds the {cap}-byte cap")
        self.length = length
        self.cap = cap
        self.fatal = fatal


def _recv_exact_timed(sock: socket.socket, n: int,
                      first_s: Optional[float],
                      rest_s: Optional[float]) -> Optional[bytes]:
    """`_read_exact` with per-recv deadlines: the FIRST byte waits under
    ``first_s`` (the idle window when reading a header), later bytes
    under ``rest_s`` (the per-frame transfer window).  None on EOF at a
    clean boundary; ConnectionError on EOF mid-buffer (truncated frame);
    :class:`_SessionTimeout` when a window expires."""
    buf = bytearray()
    while len(buf) < n:
        sock.settimeout(first_s if not buf else rest_s)
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise _SessionTimeout("idle" if not buf else "frame") from None
        if not chunk:
            if not buf:
                return None
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def _discard_exact(sock: socket.socket, n: int,
                   timeout_s: Optional[float]) -> None:
    """Consume exactly ``n`` payload bytes without retaining them (the
    over-cap skip path): bounded memory whatever the announced length."""
    remaining = n
    sock.settimeout(timeout_s)
    while remaining > 0:
        try:
            chunk = sock.recv(min(remaining, 1 << 16))
        except socket.timeout:
            raise _SessionTimeout("frame") from None
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({n - remaining}/{n} bytes)"
            )
        remaining -= len(chunk)


def _linger_drain(sock: socket.socket, deadline_s: float = _LINGER_DRAIN_S,
                  max_bytes: int = _LINGER_DRAIN_BYTES) -> None:
    """Best-effort read-to-EOF before closing after a terminal error
    response: a peer mid-send must be allowed to finish (or go quiet) so
    close() doesn't RST away the buffered error frame.  Bounded by wall
    AND bytes — courtesy, not an obligation to a hostile peer."""
    end = time.monotonic() + deadline_s
    seen = 0
    try:
        sock.settimeout(0.1)
        while time.monotonic() < end and seen < max_bytes:
            try:
                chunk = sock.recv(1 << 16)
            except socket.timeout:
                continue
            if not chunk:
                return
            seen += len(chunk)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ServiceLimits:
    """Every serving-tier limit in one place (docs/SERVICE.md has the
    ops-facing table).  Defaults are production-sane: generous enough
    that a well-behaved client never notices them, finite enough that a
    hostile or wedged one cannot take the process down."""

    max_sessions: int = 64          # concurrent admitted sessions
    max_inflight: int = 0           # concurrent parsing requests (0 = sessions)
    frame_timeout_s: Optional[float] = 30.0   # per-recv mid-frame stall window
    idle_timeout_s: Optional[float] = 600.0   # between-frames session window
    request_deadline_s: Optional[float] = None  # per-request parse deadline
    max_frame_bytes: int = _MAX_FRAME         # absolute frame ceiling
    max_config_bytes: int = 1 << 20           # CONFIG payload cap (1 MiB)
    max_lines_bytes: int = 0                  # LINES payload cap (0 = frame cap)
    busy_retry_after_s: float = 0.25          # BUSY frame retry hint
    backpressure_threshold: float = 0.95      # feeder-queue shed fraction
    drain_deadline_s: float = 10.0            # graceful-drain budget
    # Continuous batching (docs/SERVICE.md "Continuous batching"):
    # cross-session device-batch coalescing, keyed per compiled-parser
    # config.  window = how long a forming batch waits for stragglers
    # (only when >1 session is live); max_lines = the shared batch
    # geometry ceiling; queue_depth = the bounded submission queue
    # (full = structured BUSY{coalesce_queue} shed).
    coalesce: bool = True
    coalesce_window_ms: float = 2.0
    coalesce_max_lines: int = 4096
    coalesce_queue_depth: int = 256

    @property
    def inflight(self) -> int:
        return self.max_inflight or self.max_sessions

    @property
    def lines_cap(self) -> int:
        return self.max_lines_bytes or self.max_frame_bytes


class _ParserCache:
    """LRU-bounded: each entry pins a compiled parser + XLA executables, so
    a long-lived sidecar serving many distinct configs must evict."""

    def __init__(self, max_entries: int = 32,
                 on_insert: Optional[Callable[[Any], None]] = None) -> None:
        self._lock = threading.Lock()
        self._max_entries = max_entries
        self._parsers: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._building: Dict[Tuple, threading.Lock] = {}
        # Called once per freshly BUILT parser (cache hits skip it): the
        # serving tier hooks the background shape-bucket prewarmer here so
        # larger buckets — and the coalesced-batch shape — compile (or
        # load from the persistent compile cache, docs/COMPILE.md) off the
        # request path.
        self._on_insert = on_insert

    @staticmethod
    def key_of(config: Dict[str, Any]) -> Tuple:
        """The compiled-parser identity of a CONFIG: sessions with the
        same key share one parser — and one continuous-batching lane
        (requests coalesce ONLY within a key: a shared device batch must
        run exactly one compiled program)."""
        agg = config.get("aggregate")
        if agg is not None:
            # Analytics pushdown (PROTOCOL.md "aggregate"): per-session
            # specs key the parser cache, so an aggregate session never
            # shares a compiled-reduction cache — or a continuous-
            # batching lane — with a row session or a different spec.
            from .analytics.spec import parse_aggregate_config

            agg = parse_aggregate_config(agg).canonical_key()
        return (
            config["log_format"],
            tuple(config["fields"]),
            config.get("timestamp_format"),
            config.get("assembly_workers"),
            agg,
        )

    def get(self, config: Dict[str, Any]):
        from .tpu.batch import TpuBatchParser

        key = self.key_of(config)
        # Compile outside the global lock: a cold compile takes seconds and
        # must not stall sessions whose parser is already cached.  A per-key
        # lock still deduplicates concurrent compiles of the same config.
        with self._lock:
            parser = self._parsers.get(key)
            if parser is not None:
                self._parsers.move_to_end(key)
                return parser
            key_lock = self._building.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:
                parser = self._parsers.get(key)
                if parser is not None:
                    self._parsers.move_to_end(key)
            if parser is None:
                try:
                    parser = TpuBatchParser(
                        config["log_format"],
                        list(config["fields"]),
                        timestamp_format=config.get("timestamp_format"),
                        # The wire delivers copy-mode Arrow only, so the
                        # parser never needs device view rows.
                        view_fields=(),
                        assembly_workers=config.get("assembly_workers"),
                    )
                    with self._lock:
                        self._parsers[key] = parser
                        while len(self._parsers) > self._max_entries:
                            self._parsers.popitem(last=False)
                    if self._on_insert is not None:
                        # Outside the cache lock: the hook only ENQUEUES
                        # (the prewarm itself runs on the worker thread),
                        # and a hook failure must never fail the build
                        # that already succeeded.
                        try:
                            self._on_insert(parser)
                        except Exception:  # noqa: BLE001
                            LOG.warning("parser prewarm enqueue failed",
                                        exc_info=True)
                finally:
                    # Failed builds must also drop the per-key build lock:
                    # the parser LRU is bounded but _building is not, and a
                    # long-lived sidecar fed many invalid configs would
                    # otherwise grow it without bound.
                    with self._lock:
                        self._building.pop(key, None)
            return parser


class _PrewarmWorker:
    """Background shape-bucket prewarm (docs/COMPILE.md "Fleet prewarm").

    Every freshly built parser is walked up the bucket ladder — including
    the coalesced-batch shape when continuous batching is on — on ONE
    daemon thread, so no request ever waits on a compile for a bucket it
    did not itself need first.  With ``LOGPARSER_TPU_COMPILE_CACHE`` set,
    each rung is a disk deserialize (or an in-memory no-op) instead of an
    XLA compile; the per-rung source lands in
    ``parser_prewarm_shapes_total{source=memory|disk|compiled}``.

    Env knobs:

    - ``LOGPARSER_TPU_PREWARM=0``       disable entirely
    - ``LOGPARSER_TPU_PREWARM_BUCKETS`` comma-separated batch sizes
      (default: the compile cache's ``DEFAULT_BUCKET_LADDER``)
    - ``LOGPARSER_TPU_PREWARM_LINE_LEN`` line-length to warm at
      (bucketed; default 256 — the common access-log ballpark)
    """

    _STOP = object()

    @staticmethod
    def enabled() -> bool:
        return os.environ.get(
            "LOGPARSER_TPU_PREWARM", "1"
        ).strip().lower() not in ("0", "false", "no")

    def __init__(self, limits: ServiceLimits) -> None:
        self._limits = limits
        self._queue: "queue.Queue[Any]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="logparser-tpu-prewarm", daemon=True
        )
        self._thread.start()

    def ladder(self) -> Tuple[int, ...]:
        raw = os.environ.get("LOGPARSER_TPU_PREWARM_BUCKETS", "").strip()
        if raw:
            buckets = [int(t) for t in raw.split(",") if t.strip()]
        else:
            from .tpu.compile_cache import DEFAULT_BUCKET_LADDER

            buckets = list(DEFAULT_BUCKET_LADDER)
        if self._limits.coalesce:
            # The coalescer dispatches full windows at coalesce_max_lines:
            # that shape is the steady-state hot path under load and must
            # never compile on a request's clock.
            buckets.append(self._limits.coalesce_max_lines)
        return tuple(sorted({int(b) for b in buckets if int(b) > 0}))

    @staticmethod
    def line_len() -> int:
        try:
            return max(1, int(os.environ.get(
                "LOGPARSER_TPU_PREWARM_LINE_LEN", "256")))
        except ValueError:
            return 256

    def enqueue(self, parser: Any) -> None:
        self._queue.put(parser)

    def shutdown(self, timeout_s: float = 2.0) -> None:
        """Best-effort stop: the thread is a daemon, so this only bounds
        how long a graceful shutdown waits for an in-flight warm rung."""
        self._queue.put(self._STOP)
        self._thread.join(timeout=timeout_s)

    def _run(self) -> None:
        while True:
            parser = self._queue.get()
            if parser is self._STOP:
                return
            try:
                t0 = time.perf_counter()
                sources = parser.prewarm(
                    batch_sizes=self.ladder(), max_line_len=self.line_len()
                )
                reg = metrics()
                for source in sources.values():
                    reg.increment("parser_prewarm_shapes_total", 1,
                                  labels={"source": source})
                reg.increment("parser_prewarm_seconds_total",
                              time.perf_counter() - t0)
                # One tick per completed parser walk: pollable by smokes
                # and the bench ("is the ladder warm yet?") where the
                # seconds/shapes counters alone cannot distinguish one
                # finished walk from one still in flight.
                reg.increment("parser_prewarm_runs_total", 1)
                LOG.info("prewarm: %d shapes ready in %.2fs (%s)",
                         len(sources), time.perf_counter() - t0,
                         ", ".join(f"{k}={v}"
                                   for k, v in sorted(sources.items())))
            except Exception:  # noqa: BLE001 — prewarm is an optimization;
                # a failure means first requests pay the compile, nothing
                # worse, and the error class is visible in the counter.
                metrics().increment("parser_prewarm_errors_total", 1)
                LOG.warning("background prewarm failed", exc_info=True)


class _ServiceServer(socketserver.ThreadingTCPServer):
    """The listener plus all shared serving-tier state the per-session
    handlers coordinate through: the session/in-flight budgets, the live
    session registry (the drain machinery's ledger), and the draining
    flag (readiness)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler, limits: ServiceLimits):
        super().__init__(addr, handler)
        self.limits = limits
        # Background shape-bucket prewarmer (docs/COMPILE.md): freshly
        # built parsers walk the bucket ladder off the request path.
        self.prewarmer: Optional[_PrewarmWorker] = (
            _PrewarmWorker(limits) if _PrewarmWorker.enabled() else None
        )
        self.parser_cache = _ParserCache(
            on_insert=(self.prewarmer.enqueue
                       if self.prewarmer is not None else None)
        )
        self.session_seq = itertools.count(1)
        self.session_slots = threading.BoundedSemaphore(limits.max_sessions)
        self.inflight_slots = threading.BoundedSemaphore(limits.inflight)
        self.sessions: Dict[Any, threading.Thread] = {}
        self.sessions_lock = threading.Lock()
        self.key_sessions: Dict[Any, int] = {}
        self.draining = False
        # Cross-session batch coalescer (service_batching.py), attached
        # by ParseService when limits.coalesce is on; None = every
        # request dispatches its own device batch (the pre-round-14
        # behavior, and the bench A/B baseline).
        self.coalescer: Optional[Any] = None

    def admitted_sessions(self) -> int:
        with self.sessions_lock:
            return sum(1 for h in self.sessions if h.admitted)

    # Sessions per PARSER KEY (registered once the CONFIG resolves,
    # dropped when the session ends): the coalescer's window is only
    # worth paying when another session on the SAME key could
    # contribute — a global count would make a lone tenant on its own
    # format pay the window because an unrelated format has traffic.
    def key_session_enter(self, key: Any) -> None:
        with self.sessions_lock:
            self.key_sessions[key] = self.key_sessions.get(key, 0) + 1

    def key_session_exit(self, key: Any) -> None:
        with self.sessions_lock:
            n = self.key_sessions.get(key, 0) - 1
            if n > 0:
                self.key_sessions[key] = n
            else:
                self.key_sessions.pop(key, None)

    def sessions_on_key(self, key: Any) -> int:
        with self.sessions_lock:
            return self.key_sessions.get(key, 0)

    def admit_request(self) -> Optional[str]:
        """Per-request admission: None = admitted (ONE in-flight slot is
        now held by the caller); otherwise the shed reason.  The
        backpressure leg reads the feeder fabric's queue-occupancy
        signal (docs/FEEDER.md): framed batches waiting at/above the
        threshold fraction of bounded-queue capacity mean the parser is
        the bottleneck and queueing more requests only grows latency."""
        if not self.inflight_slots.acquire(blocking=False):
            return "inflight"
        from .feeder import queue_backpressure

        if queue_backpressure() >= self.limits.backpressure_threshold:
            self.release_request()
            return "backpressure"
        metrics().gauge_add("service_inflight_requests", 1)
        return None

    def release_request(self, gauged: bool = False) -> None:
        self.inflight_slots.release()
        if gauged:
            metrics().gauge_add("service_inflight_requests", -1)

    def handle_error(self, request, client_address) -> None:  # noqa: D102
        # socketserver's default prints a traceback to stderr; a hostile
        # wire must never be able to scribble on the operator's console.
        LOG.exception("unhandled session error from %s", client_address)


class _SessionHandler(socketserver.BaseRequestHandler):
    server: _ServiceServer  # narrowed for type checkers

    # -- lifecycle ------------------------------------------------------

    def setup(self) -> None:
        self.sid = next(self.server.session_seq)
        self.thread = threading.current_thread()
        # Named handler threads + sid-tagged logs: overload drills must be
        # debuggable from a thread dump / log tail alone.
        self.thread.name = f"svc-sess-{self.sid}"
        self.admitted = False
        with self.server.sessions_lock:
            self.server.sessions[self] = self.thread

    def finish(self) -> None:
        with self.server.sessions_lock:
            self.server.sessions.pop(self, None)
        if self.admitted:
            self.server.session_slots.release()
            metrics().gauge_add("service_sessions_active", -1)

    # -- helpers --------------------------------------------------------

    def _read_frame(self, payload_cap: int,
                    discard_over_cap: bool) -> Optional[bytes]:
        """One frame under the session's deadlines and ceilings; the
        length prefix is validated BEFORE any payload allocation."""
        lim = self.server.limits
        sock = self.request
        header = _recv_exact_timed(
            sock, 4, lim.idle_timeout_s, lim.frame_timeout_s
        )
        if header is None:
            return None
        (length,) = struct.unpack(">I", header)
        if length == 0:
            return None
        if length == _ERROR_MARKER:
            raise ParseServiceError("client sent an error marker frame")
        if length > lim.max_frame_bytes:
            raise _FrameTooLarge(length, lim.max_frame_bytes, fatal=True)
        if length > payload_cap:
            if not discard_over_cap:
                raise _FrameTooLarge(length, payload_cap, fatal=True)
            _discard_exact(sock, length, lim.frame_timeout_s)
            raise _FrameTooLarge(length, payload_cap, fatal=False)
        payload = _recv_exact_timed(
            sock, length, lim.frame_timeout_s, lim.frame_timeout_s
        )
        if payload is None:
            raise ConnectionError(f"peer closed mid-frame (0/{length} bytes)")
        return payload

    def _pre_write(self) -> None:
        """Arm the socket for a RESPONSE write: the per-frame READ window
        a prior ``_recv_exact_timed`` left on the socket must not govern
        ``sendall`` — a large Arrow frame on a slow link legitimately
        needs minutes, and CPython enforces the socket timeout as a
        TOTAL sendall deadline.  The idle window (generous, still
        bounded against a peer that stops reading entirely) applies to
        writes instead."""
        try:
            self.request.settimeout(self.server.limits.idle_timeout_s)
        except OSError:
            pass

    def _shed_session(self, reason: str) -> None:
        """Refuse this connection with a structured BUSY frame (never a
        reset): write the frame, let the peer finish/acknowledge, close."""
        lim = self.server.limits
        metrics().increment("service_shed_total", labels={"reason": reason})
        LOG.info("sess=%d shed (%s)", self.sid, reason)
        try:
            self._pre_write()
            write_error(
                self.request, busy_error_text(reason, lim.busy_retry_after_s)
            )
            _linger_drain(self.request)
        except OSError:
            pass

    def _timeout(self, kind: str) -> None:
        metrics().increment("service_timeouts_total", labels={"kind": kind})
        LOG.info("sess=%d %s timeout; closing session", self.sid, kind)

    def _reject_frame(self, reason: str, message: str,
                      fatal: bool) -> bool:
        """Answer an over-limit frame with one error frame; returns
        whether the session can continue (non-fatal = payload was
        consumed, resync is safe)."""
        metrics().increment(
            "service_rejected_frames_total", labels={"reason": reason}
        )
        LOG.warning("sess=%d rejected frame (%s): %s", self.sid, reason,
                    message)
        try:
            self._pre_write()
            write_error(self.request, message)
            if fatal:
                _linger_drain(self.request)
        except OSError:
            return False
        return not fatal

    # -- the session ----------------------------------------------------

    def handle(self) -> None:  # noqa: D102 — socketserver contract
        try:
            if self.server.draining:
                self._shed_session("draining")
                return
            if not self.server.session_slots.acquire(blocking=False):
                self._shed_session("sessions")
                return
            self.admitted = True
            metrics().gauge_add("service_sessions_active", 1)
            self._session()
        except Exception:  # noqa: BLE001 — a session must never kill/print
            LOG.exception("sess=%d unhandled session failure", self.sid)

    def _config_error_loop(self, message: str) -> None:
        """Relay a config error, then keep draining the session answering
        every subsequent frame with the same error: a client already
        mid-send of a large LINES frame would otherwise see ECONNRESET
        and the RST can discard the buffered error text."""
        sock = self.request
        lim = self.server.limits
        try:
            self._pre_write()
            write_error(sock, message)
            while True:
                try:
                    if self._read_frame(lim.lines_cap, True) is None:
                        return
                except _FrameTooLarge as e:
                    if e.fatal:
                        _linger_drain(sock)
                        return
                self._pre_write()
                write_error(sock, message)
        except (OSError, ValueError, ConnectionError, ParseServiceError):
            return
        except _SessionTimeout as e:
            self._timeout(e.kind)
            return

    def _session(self) -> None:
        sock = self.request
        lim = self.server.limits
        try:
            config_frame = self._read_frame(lim.max_config_bytes, True)
        except _SessionTimeout as e:
            self._timeout(e.kind)
            return
        except _FrameTooLarge as e:
            if e.fatal:
                self._reject_frame(
                    "frame_overflow", f"bad config: {e}", fatal=True
                )
            else:
                metrics().increment(
                    "service_rejected_frames_total",
                    labels={"reason": "config_too_large"},
                )
                self._config_error_loop(f"bad config: {e}")
            return
        except (ValueError, OSError, ParseServiceError) as e:
            if isinstance(e, OSError) and not isinstance(e, ConnectionError):
                # Our own force-close (shutdown/drain escalation) lands
                # here as EBADF/ENOTCONN on the blocked recv: routine.
                LOG.info("sess=%d socket closed during config read: %s",
                         self.sid, e)
            else:
                LOG.error("sess=%d bad config frame: %s", self.sid, e)
            return
        if config_frame is None:
            return
        send_stats = False
        feeder_workers = 0
        try:
            config = json.loads(config_frame)
            # Optional telemetry opt-in (PROTOCOL.md "stats" CONFIG key):
            # absent/falsy = byte-identical v1 session.  Not part of the
            # parser cache key — it changes framing, not parsing.
            send_stats = bool(config.get("stats")) if isinstance(
                config, dict) else False
            # Optional sharded-feeder framing (docs/FEEDER.md): >= 2 =
            # big LINES payloads are framed by that many feeder threads
            # over byte-range shards.  Session behavior, not parser
            # state — not part of the cache key either.
            if isinstance(config, dict) and config.get("feeder_workers"):
                feeder_workers = int(config["feeder_workers"])
            # Client batching hint (PROTOCOL.md "coalesce_wait_ms"): a
            # latency-critical session caps the coalescer's straggler
            # window for ITS requests (0 = dispatch immediately once
            # claimed).  Session behavior only — results are
            # byte-identical, so not part of the cache key either.
            coalesce_wait_s: Optional[float] = None
            if isinstance(config, dict) \
                    and config.get("coalesce_wait_ms") is not None:
                coalesce_wait_s = float(config["coalesce_wait_ms"]) / 1e3
                if coalesce_wait_s < 0:
                    raise ValueError(
                        "coalesce_wait_ms must be >= 0, got "
                        f"{config['coalesce_wait_ms']!r}"
                    )
            # Analytics pushdown (PROTOCOL.md "aggregate" / docs/
            # ANALYTICS.md): the session's responses become aggregate
            # frames instead of row Arrow.  Spec errors — bad JSON, an
            # unknown op, a field outside the parse config — relay
            # through the same "bad config:" loop as every other
            # config defect.
            agg_spec = None
            if isinstance(config, dict) \
                    and config.get("aggregate") is not None:
                from .analytics.spec import parse_aggregate_config

                agg_spec = parse_aggregate_config(config["aggregate"])
            # Distributed tracing context (PROTOCOL.md "traceparent"):
            # wire-invisible when absent (byte-identical v1 session);
            # a malformed value is silently DROPPED, never a config
            # error — the W3C contract is that bad trace plumbing must
            # not break the request.  Session behavior only — results
            # are identical either way, so not part of the cache key.
            trace_ctx = None
            if isinstance(config, dict) and config.get("traceparent"):
                trace_ctx = parse_traceparent(config.get("traceparent"))
            parser = self.server.parser_cache.get(config)
            if agg_spec is not None:
                agg_spec.validate_for(parser)
            metrics().increment("service_sessions_total")
        except Exception as e:  # noqa: BLE001 — relay config errors to client
            self._config_error_loop(f"bad config: {e}")
            return

        try:
            parser_key = _ParserCache.key_of(config)
        except Exception:  # noqa: BLE001 — doubles may bypass the schema
            parser_key = repr(config)
        state = {"feeder_workers": feeder_workers,
                 "parser_key": parser_key,
                 "coalesce_wait_s": coalesce_wait_s,
                 "aggregate": agg_spec,
                 "trace_ctx": trace_ctx}
        # Per-key session registry: the coalescer skips its straggler
        # window when this session is the key's only one.
        self.server.key_session_enter(parser_key)
        try:
            while True:
                try:
                    lines_frame = self._read_frame(lim.lines_cap, True)
                except _SessionTimeout as e:
                    self._timeout(e.kind)
                    return
                except _FrameTooLarge as e:
                    if not self._reject_frame(
                        "frame_overflow" if e.fatal else "lines_too_large",
                        f"rejected: {e}", fatal=e.fatal,
                    ):
                        return
                    continue
                except (ValueError, OSError, ParseServiceError) as e:
                    if isinstance(e, OSError) and not isinstance(
                            e, ConnectionError):
                        LOG.info("sess=%d socket closed between frames: %s",
                                 self.sid, e)
                    else:
                        LOG.error("sess=%d bad lines frame: %s", self.sid, e)
                    return
                if lines_frame is None:
                    return  # end of session
                if not self._serve_request(sock, parser, lines_frame, state,
                                           send_stats):
                    return
        finally:
            self.server.key_session_exit(parser_key)

    # -- one request ----------------------------------------------------

    def _serve_request(self, sock, parser, lines_frame: bytes,
                       state: Dict[str, Any], send_stats: bool) -> bool:
        """One LINES frame -> one response frame (ARROW / BUSY / DEADLINE
        / error).  Returns False only when the socket died."""
        reg = metrics()
        lim = self.server.limits
        # Request span (docs/OBSERVABILITY.md "Tracing"): opened only
        # for sampled sessions; its context rides state["request_ctx"]
        # into the coalescer so the shared-batch span links back here.
        req_span = child_span("service_request", state.get("trace_ctx"),
                              attrs={"sid": self.sid})
        if req_span is not None:
            state["request_ctx"] = req_span.context
        # Every response write in this method (BUSY/DEADLINE/error/ARROW/
        # STATS) runs under the idle window, not the leftover read window.
        self._pre_write()
        shed_reason = self.server.admit_request()
        if shed_reason is not None:
            reg.increment("service_shed_total",
                          labels={"reason": shed_reason})
            flight_event("service_shed", reason=shed_reason, sid=self.sid)
            if req_span is not None:
                req_span.end(outcome="shed", reason=shed_reason)
            LOG.info("sess=%d request shed (%s)", self.sid, shed_reason)
            try:
                write_error(sock, busy_error_text(
                    shed_reason, lim.busy_retry_after_s))
            except OSError:
                return False
            return True

        t_request = time.perf_counter()
        done, outcome = self._run_admitted(
            lambda: self._parse_request(parser, lines_frame, state)
        )
        if not done:
            # Deadline expired: the parse keeps running in its worker
            # (releasing the in-flight slot when it truly finishes — a
            # stuck parse keeps its slot, which IS the backpressure);
            # the session answers and moves on.
            reg.increment("service_deadline_expired_total")
            flight_event("service_deadline_expired", sid=self.sid,
                         deadline_s=lim.request_deadline_s or 0.0)
            if req_span is not None:
                req_span.end(outcome="deadline")
            LOG.warning("sess=%d request deadline (%.3fs) expired",
                        self.sid, lim.request_deadline_s or 0.0)
            try:
                write_error(sock, deadline_error_text(
                    lim.request_deadline_s or 0.0))
            except OSError:
                return False
            return True
        if isinstance(outcome, Exception):
            from .service_batching import (
                CoalesceDeadline,
                CoalesceQueueFull,
            )

            if isinstance(outcome, CoalesceQueueFull):
                # The coalescer's bounded submission queue is full: shed
                # STRUCTURED, exactly like the admission legs — never an
                # opaque parse error (docs/SERVICE.md).
                reg.increment("service_shed_total",
                              labels={"reason": "coalesce_queue"})
                flight_event("service_shed", reason="coalesce_queue",
                             sid=self.sid)
                if req_span is not None:
                    req_span.end(outcome="shed", reason="coalesce_queue")
                LOG.info("sess=%d request shed (coalesce_queue)", self.sid)
                try:
                    write_error(sock, busy_error_text(
                        "coalesce_queue", lim.busy_retry_after_s))
                except OSError:
                    return False
                return True
            if isinstance(outcome, CoalesceDeadline):
                # Expired while QUEUED (dropped before batch formation):
                # the same structured DEADLINE answer an expired solo
                # parse gets, and the session survives.
                reg.increment("service_deadline_expired_total")
                flight_event("service_deadline_expired", sid=self.sid,
                             where="coalesce_queue",
                             deadline_s=lim.request_deadline_s or 0.0)
                if req_span is not None:
                    req_span.end(outcome="deadline",
                                 where="coalesce_queue")
                LOG.warning(
                    "sess=%d request deadline (%.3fs) expired in the "
                    "coalesce queue", self.sid,
                    lim.request_deadline_s or 0.0,
                )
                try:
                    write_error(sock, deadline_error_text(
                        lim.request_deadline_s or 0.0))
                except OSError:
                    return False
                return True
            from .tpu.device_faults import DeviceBudgetError

            if isinstance(outcome, DeviceBudgetError):
                # Pre-allocation device-byte ceiling (docs/FAULTS.md):
                # the batch was refused BEFORE any device_put — a
                # structured reject like the frame ceilings, not an
                # opaque parse failure (the session survives; the
                # client should split its payload).
                reg.increment("service_rejected_frames_total",
                              labels={"reason": "device_budget"})
                if req_span is not None:
                    req_span.end(outcome="rejected", reason="device_budget")
                LOG.warning("sess=%d request rejected (device_budget): "
                            "%s", self.sid, outcome)
                try:
                    write_error(sock, f"parse failed: {outcome}")
                except OSError:
                    return False
                return True
            LOG.error("sess=%d parse failed", self.sid, exc_info=outcome)
            reg.increment("service_request_errors_total")
            if req_span is not None:
                req_span.end(outcome="error",
                             error=f"{type(outcome).__name__}: {outcome}")
            try:
                write_error(sock, f"parse failed: {outcome}")
            except OSError:
                return False
            return True

        payload, count, oracle_rows, bad_lines = outcome
        try:
            write_frame(sock, payload)
        except OSError:
            return False
        dt = time.perf_counter() - t_request
        reg.increment("service_requests_total")
        reg.increment("service_lines_total", count)
        reg.observe("service_request_seconds", dt)
        if req_span is not None:
            req_span.end(outcome="ok", lines=count)
        if send_stats:
            # STATS frame: per-request figures + the SAME
            # process-cumulative stage breakdown /metrics and
            # bench.py report (one metric definition everywhere).
            stats = {
                "v": 1,
                "request": {
                    "lines": count,
                    "seconds": round(dt, 6),
                    "arrow_bytes": len(payload),
                    "oracle_lines": oracle_rows,
                    "bad_lines": bad_lines,
                },
                "stages": reg.stage_breakdown(),
                # as_dict(): counters only — snapshot() would build
                # every histogram's bucket view per request.
                "counters": dict(sorted(reg.as_dict().items())),
            }
            try:
                write_frame(
                    sock,
                    json.dumps(stats, separators=(",", ":"),
                               sort_keys=True).encode("utf-8"),
                )
            except OSError:
                return False
        return True

    def _run_admitted(self, fn: Callable[[], Any]) -> Tuple[bool, Any]:
        """Run one ADMITTED request under the parse deadline.  The
        in-flight slot is released when the WORK finishes — even after
        its deadline already expired — so abandoned parses keep counting
        against the budget until they actually stop consuming the host.
        Returns ``(completed, result-or-exception)``."""
        server = self.server
        deadline = server.limits.request_deadline_s
        if not deadline:
            try:
                return True, fn()
            except Exception as e:  # noqa: BLE001 — relayed as error frame
                return True, e
            finally:
                server.release_request(gauged=True)

        box: Dict[str, Any] = {}
        done = threading.Event()
        abandoned = threading.Event()

        def run() -> None:
            try:
                box["value"] = fn()
            except Exception as e:  # noqa: BLE001 — relayed / logged
                box["error"] = e
            finally:
                server.release_request(gauged=True)
                done.set()
                if abandoned.is_set():
                    LOG.debug(
                        "sess=%d abandoned request finished (%s)", self.sid,
                        "error" if "error" in box else "ok",
                    )

        worker = threading.Thread(
            target=run, name=f"svc-req-{self.sid}", daemon=True
        )
        worker.start()
        if not done.wait(deadline):
            abandoned.set()
            return False, None
        if "error" in box:
            return True, box["error"]
        return True, box["value"]

    def _parse_request(self, parser, lines_frame: bytes,
                       state: Dict[str, Any]):
        """The request body: LINES validation + parse + Arrow IPC bytes.
        Raises on anything relay-worthy; returns
        ``(ipc_payload, count, oracle_rows, bad_lines)``."""
        if len(lines_frame) < 4:
            raise ValueError("LINES frame shorter than its count header")
        (count,) = struct.unpack(">I", lines_frame[:4])
        if count == 0 and len(lines_frame) > 4:
            raise ValueError(
                "LINES frame declared 0 lines but carries "
                f"{len(lines_frame) - 4} payload bytes"
            )
        blob = lines_frame[4:]
        n_lines = (blob.count(b"\n") + 1) if count else 0
        if n_lines != count:
            raise ValueError(
                f"LINES frame declared {count} lines, payload has "
                f"{n_lines}"
            )
        blob_shape = count and blob and not blob.endswith(b"\n") \
            and b"\r" not in blob
        if state.get("aggregate") is not None:
            # Aggregate session (docs/ANALYTICS.md): the response is an
            # aggregate frame, not row Arrow, so the feeder's table
            # concatenation and the coalescer's row-window slicing
            # don't apply — aggregate requests keep their own
            # dispatch.  (They never coalesce wrongly either way:
            # the spec is part of the parser cache key, so an
            # aggregate session shares no lane with a row session.)
            spec = state["aggregate"]
            if blob_shape:
                agg_out = parser.aggregate_blob(blob, spec)
            else:
                agg_out = parser.aggregate_batch(
                    blob.split(b"\n") if count else [], spec
                )
            return (agg_out.state.to_ipc_bytes(), count,
                    agg_out.oracle_rows, agg_out.bad_lines)
        feeder_workers = state["feeder_workers"]
        table = None
        if blob_shape and feeder_workers >= 2 \
                and count >= _FEEDER_MIN_LINES:
            # Sharded-feeder framing: the blob splits into
            # byte-range shards framed by N threads in parallel;
            # result tables concatenate back in corpus order
            # (byte-identical to the inline blob path).
            try:
                table, oracle_rows, bad_lines = _feeder_parse(
                    parser, blob, count, feeder_workers
                )
                metrics().increment("service_feeder_requests_total")
            except Exception as e:  # noqa: BLE001 — degrade, not drop
                # ANY feeder-path failure demotes the SESSION:
                # its remaining LINES frames parse inline (the
                # fabric already self-heals worker crashes, so
                # reaching here means even quarantine failed —
                # don't re-enter it this session).
                from .feeder import FeederError

                state["feeder_workers"] = 0
                metrics().increment("service_feeder_demotions_total")
                log_warning_once(
                    LOG,
                    "service: sharded-feeder framing failed "
                    f"({type(e).__name__}); session demoted to "
                    "inline parsing",
                )
                if not isinstance(e, FeederError):
                    # A parse-shaped failure would fail inline
                    # too: relay it as a well-formed error frame
                    # (the session stays alive and its NEXT
                    # frame takes the inline path).
                    raise
                # A fabric failure with intact input: retry THIS
                # request inline below — the client sees an
                # error-free ARROW stream, not a dropped
                # connection or an error frame.
                LOG.error("sess=%d feeder fabric failed; request "
                          "re-parsed inline: %s", self.sid, e)
        if table is None:
            coalescer = getattr(self.server, "coalescer", None)
            if (
                coalescer is not None and blob_shape
                and count <= coalescer.max_lines
            ):
                # Continuous batching (docs/SERVICE.md): the payload
                # joins the parser key's shared submission queue and
                # comes back as this request's row window of a
                # coalesced device batch — byte-identical to the solo
                # parse below.  Oversize payloads (and the feeder path
                # above) keep their own dispatch; CR-carrying and
                # trailing-newline payloads need the exact-list
                # semantics of the split path.
                result = coalescer.parse(
                    state["parser_key"], parser, bytes(blob), count,
                    deadline_s=self.server.limits.request_deadline_s,
                    max_wait_s=state.get("coalesce_wait_s"),
                    trace_ctx=state.get("request_ctx"),
                )
            elif blob_shape:
                # (an empty blob is one empty LINE per the
                # protocol, which blob framing would drop —
                # split path below)
                # Common case: the payload IS the framer's input
                # shape (no trailing newline, no carriage
                # returns), so the blob ingest path applies — no
                # Python line list.  emit_views=False: the wire
                # ships copy-mode Arrow, so device view rows
                # would be wasted kernel + D2H.
                result = parser.parse_blob(blob, emit_views=False)
            else:
                result = parser.parse_batch(
                    blob.split(b"\n") if count else [],
                    emit_views=False,
                )
            # Copy mode for the wire: IPC does not dedupe shared
            # buffers, so string_view columns would each ship a
            # full copy of the batch buffer.
            table = result.to_arrow(include_validity=True,
                                    strings="copy")
            oracle_rows = result.oracle_rows
            bad_lines = result.bad_lines
        from .tpu.arrow_bridge import table_to_ipc_bytes

        return table_to_ipc_bytes(table), count, oracle_rows, bad_lines


def _feeder_parse(parser, blob: bytes, count: int, workers: int):
    """Parse one LINES blob through the sharded feeder fabric
    (docs/FEEDER.md): the payload splits into ``workers`` byte-range
    shards framed by feeder THREADS (a serving process must not fork,
    so the in-process ``inline`` hand-off applies — the shared-memory
    ring transport is for process pools), the parser consumes the
    encoded stream via ``parse_batch_stream`` (which also stages each
    next batch's H2D upload while the current one computes — the
    double-buffered device edge), and the per-batch tables concatenate
    back — in corpus order — into the single combined record batch the
    protocol promises.  Returns ``(table, oracle_rows, bad_lines)``."""
    import pyarrow as pa

    from .feeder import FeederPool, default_feeder_workers

    # The key is client-supplied: clamp to the host's own worker ceiling
    # so one CONFIG frame cannot spawn an arbitrary thread count.
    workers = max(2, min(workers, default_feeder_workers()))
    tables = []
    oracle_rows = 0
    bad_lines = 0
    with FeederPool(
        [blob],
        workers=workers,
        shard_bytes=max(1, -(-len(blob) // workers)),
        batch_lines=max(1024, -(-count // workers)),
        use_processes=False,
        # A per-request framing pool's full queue is its healthy steady
        # state, not fabric overload: it must not feed the process-wide
        # admission signal and shed every concurrent request.
        backpressure_signal=False,
    ) as pool:
        for result in pool.feed(parser, emit_views=False):
            tables.append(
                result.to_arrow(include_validity=True, strings="copy")
            )
            oracle_rows += result.oracle_rows
            bad_lines += result.bad_lines
    return pa.concat_tables(tables).combine_chunks(), oracle_rows, bad_lines


class _MetricsHandler(BaseHTTPRequestHandler):
    """GET /metrics -> Prometheus text exposition of the process registry;
    GET /tracez -> recent completed trace spans (JSON);
    GET /flightz -> the crash-safe flight recorder's event ring (JSON);
    GET /healthz -> liveness (200 while the process serves HTTP at all);
    GET /readyz -> readiness (200 ready, 503 once draining — the flip
    orchestrators key traffic removal on, docs/SERVICE.md)."""

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
        if path == "/metrics":
            body = metrics().prometheus_text().encode("utf-8")
            self._respond(200, body,
                          "text/plain; version=0.0.4; charset=utf-8")
            return
        if path == "/tracez":
            # Recent completed spans (docs/OBSERVABILITY.md "Tracing").
            body = json.dumps(tracez_payload(),
                              sort_keys=True).encode("utf-8")
            self._respond(200, body, "application/json")
            return
        if path == "/flightz":
            # The flight recorder's live ring (docs/OBSERVABILITY.md
            # "Flight recorder") — same payload a crash dump writes.
            body = json.dumps(flightz_payload(),
                              sort_keys=True).encode("utf-8")
            self._respond(200, body, "application/json")
            return
        if path in ("/healthz", "/readyz"):
            state_fn = getattr(self.server, "state_fn", None)
            state = dict(state_fn()) if state_fn is not None else {}
            draining = bool(state.pop("draining", False))
            if path == "/healthz":
                status, code = "ok", 200
            elif draining:
                status, code = "draining", 503
            else:
                status, code = "ready", 200
            body = json.dumps(
                {"status": status, **state}, sort_keys=True
            ).encode("utf-8")
            self._respond(code, body, "application/json")
            return
        self.send_error(404)

    def _respond(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        LOG.debug("metrics http: " + fmt, *args)


class MetricsEndpoint:
    """Standalone /metrics + /healthz + /readyz HTTP endpoint.  Owned by
    :class:`ParseService` when ``metrics_port`` is given (which supplies
    ``state_fn`` so readiness tracks the drain state); usable on its own
    for non-sidecar processes (always ready)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 state_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self._server = ThreadingHTTPServer((host, port), _MetricsHandler)
        self._server.daemon_threads = True
        self._server.state_fn = state_fn  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MetricsEndpoint":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="logparser-tpu-metrics", daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        # Like ParseService.shutdown: BaseServer.shutdown() waits on an
        # event only a running serve_forever loop sets — never call it
        # for an endpoint that was constructed but not started.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                note_teardown(
                    LOG, "service_teardown_errors_total", "metrics_join",
                    "metrics endpoint thread outlived its 5 s join",
                )


class _StatsLogger:
    """Daemon thread logging a one-line telemetry summary every
    ``interval`` seconds: request/line counters, per-stage p99s, and
    suppressed-warning counts (the end-of-run summary CappedLogger/
    log_warning_once promise)."""

    def __init__(self, interval: float):
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="logparser-tpu-stats", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.log_once()

    @staticmethod
    def log_once() -> None:
        reg = metrics()
        snap = reg.snapshot()
        summary = {
            "counters": {
                k: v for k, v in snap["counters"].items()
                if not k.startswith("stage_items_total")
            },
            "stage_p99_ms": {
                stage: d["p99_ms"]
                for stage, d in reg.stage_breakdown().items()
            },
        }
        suppressed = suppressed_warning_counts()
        if suppressed:
            summary["suppressed_warnings"] = suppressed
        LOG.info("service stats: %s", json.dumps(summary, sort_keys=True))

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                note_teardown(
                    LOG, "service_teardown_errors_total", "stats_join",
                    "stats logger thread outlived its 5 s join",
                )


class ParseService:
    """The sidecar: `with ParseService() as svc: ... svc.port ...` or call
    `serve_forever()` from a main program.

    ``metrics_port`` (int, optional): also serve the process metrics
    registry as a Prometheus ``/metrics`` HTTP endpoint — plus
    ``/healthz`` and ``/readyz`` — on that port (0 = ephemeral; read
    back via :attr:`metrics_port`).
    ``stats_interval`` (seconds, optional): log a one-line telemetry
    summary periodically at INFO level.

    Every serving limit (admission budgets, deadlines, payload caps,
    drain budget — docs/SERVICE.md) is a keyword knob mirroring a
    :class:`ServiceLimits` field."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics_port: Optional[int] = None,
                 stats_interval: Optional[float] = None,
                 *,
                 max_sessions: int = 64,
                 max_inflight: int = 0,
                 frame_timeout_s: Optional[float] = 30.0,
                 idle_timeout_s: Optional[float] = 600.0,
                 request_deadline_s: Optional[float] = None,
                 max_frame_bytes: int = _MAX_FRAME,
                 max_config_bytes: int = 1 << 20,
                 max_lines_bytes: int = 0,
                 busy_retry_after_s: float = 0.25,
                 backpressure_threshold: float = 0.95,
                 drain_deadline_s: float = 10.0,
                 coalesce: Optional[bool] = None,
                 coalesce_window_ms: Optional[float] = None,
                 coalesce_max_lines: Optional[int] = None,
                 coalesce_queue_depth: Optional[int] = None):
        def _window(v: Optional[float]) -> Optional[float]:
            # <= 0 means "disabled", like request_deadline_s/max_inflight:
            # settimeout(0.0) would mean NON-BLOCKING and instantly kill
            # every session — never let that spelling through.
            return float(v) if v and v > 0 else None

        defaults = ServiceLimits()
        if coalesce is None:
            # Env kill switch (docs/SERVICE.md): continuous batching is
            # ON by default — it is byte-transparent on the wire — but
            # an operator can hard-disable it without a code change.
            coalesce = os.environ.get(
                "LOGPARSER_TPU_COALESCE", "1"
            ).strip().lower() not in ("0", "false", "no")
        self.limits = ServiceLimits(
            max_sessions=int(max_sessions),
            max_inflight=int(max_inflight),
            frame_timeout_s=_window(frame_timeout_s),
            idle_timeout_s=_window(idle_timeout_s),
            request_deadline_s=_window(request_deadline_s),
            max_frame_bytes=int(max_frame_bytes),
            max_config_bytes=int(max_config_bytes),
            max_lines_bytes=int(max_lines_bytes),
            busy_retry_after_s=float(busy_retry_after_s),
            backpressure_threshold=float(backpressure_threshold),
            drain_deadline_s=float(drain_deadline_s),
            coalesce=bool(coalesce),
            coalesce_window_ms=float(
                defaults.coalesce_window_ms if coalesce_window_ms is None
                else coalesce_window_ms
            ),
            coalesce_max_lines=int(
                defaults.coalesce_max_lines if coalesce_max_lines is None
                else coalesce_max_lines
            ),
            coalesce_queue_depth=int(
                defaults.coalesce_queue_depth if coalesce_queue_depth is None
                else coalesce_queue_depth
            ),
        )
        self._server = _ServiceServer((host, port), _SessionHandler,
                                      self.limits)
        if self.limits.coalesce:
            from .service_batching import BatchCoalescer

            self._server.coalescer = BatchCoalescer(
                window_s=self.limits.coalesce_window_ms / 1000.0,
                max_lines=self.limits.coalesce_max_lines,
                queue_depth=self.limits.coalesce_queue_depth,
                live_sessions_fn=self._server.sessions_on_key,
            )
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._closed = False
        self._close_lock = threading.Lock()
        self._teardown_done = threading.Event()
        self._metrics: Optional[MetricsEndpoint] = None
        if metrics_port is not None:
            self._metrics = MetricsEndpoint(host, metrics_port,
                                            state_fn=self._health_state)
        self._stats_logger: Optional[_StatsLogger] = None
        if stats_interval:
            self._stats_logger = _StatsLogger(float(stats_interval))

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def metrics_port(self) -> Optional[int]:
        """The bound /metrics HTTP port (None when not enabled)."""
        return self._metrics.port if self._metrics is not None else None

    @property
    def draining(self) -> bool:
        return self._server.draining

    def _health_state(self) -> Dict[str, Any]:
        # Admitted sessions only — matching the service_sessions_active
        # gauge and the max_sessions budget reported beside it.  Handlers
        # mid-BUSY-shed linger are refused connections, not sessions.
        with self._server.sessions_lock:
            active = sum(
                1 for h in self._server.sessions if h.admitted
            )
        return {
            "draining": self._server.draining,
            "sessions_active": active,
            "max_sessions": self.limits.max_sessions,
        }

    def _start_sidecars(self) -> None:
        log_version_banner_once(LOG)
        if self._metrics is not None:
            self._metrics.start()
            LOG.info("serving /metrics + /healthz + /readyz on port %d",
                     self._metrics.port)
        if self._stats_logger is not None:
            self._stats_logger.start()

    def start(self) -> "ParseService":
        self._serving = True
        self._start_sidecars()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="logparser-tpu-service",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._start_sidecars()
        self._server.serve_forever()

    # -- teardown -------------------------------------------------------

    def _session_snapshot(self) -> List[Tuple[Any, threading.Thread]]:
        with self._server.sessions_lock:
            return list(self._server.sessions.items())

    def _await_sessions(self, deadline_s: float) -> bool:
        """Wait (poll) until every ADMITTED session ends; False when the
        drain deadline expired with admitted sessions still live.  Only
        admitted sessions gate the drain: while it runs the listener is
        still up shedding BUSY{draining}, and those short-lived shed
        handlers must not be able to hold the drain open forever."""
        def admitted_live() -> bool:
            with self._server.sessions_lock:
                return any(h.admitted for h in self._server.sessions)

        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            if not admitted_live():
                return True
            time.sleep(0.02)
        return not admitted_live()

    def _force_close_sessions(self, site: str, count: bool) -> None:
        for handler, _thread in self._session_snapshot():
            # Only ADMITTED sessions count as drain-deadline leaks — a
            # transient shed handler mid-linger is a refused connection,
            # not work that outlived the drain (its socket still gets
            # closed below).
            if count and handler.admitted:
                note_teardown(
                    LOG, "service_teardown_errors_total", site,
                    f"session {handler.sid} outlived the drain deadline; "
                    "force-closing its socket",
                )
            for closer in (
                lambda: handler.request.shutdown(socket.SHUT_RDWR),
                handler.request.close,
            ):
                try:
                    closer()
                except OSError:
                    pass

    def _join_sessions(self, budget_s: float = 5.0) -> None:
        # ONE shared budget across all leaked sessions: per-thread
        # timeouts would stack (64 wedged sessions x 2 s each) far past
        # any drain deadline, stalling every concurrent shutdown() waiter.
        end = time.monotonic() + budget_s
        for _handler, thread in self._session_snapshot():
            thread.join(timeout=max(0.0, end - time.monotonic()))
            if thread.is_alive():
                note_teardown(
                    LOG, "service_teardown_errors_total", "session_join",
                    f"session thread {thread.name} outlived its join after "
                    "socket close",
                )

    def shutdown(self, drain: bool = False,
                 drain_deadline_s: Optional[float] = None) -> None:
        """Stop the service.  ``drain=False``: immediate — stop accepting
        and force-close any live session (clients mid-request see EOF).
        ``drain=True``: graceful — flip ``/readyz`` to draining FIRST
        (so orchestrators stop routing before the listener goes away),
        stop accepting, let admitted sessions finish under the drain
        deadline, then escalate force-close -> join.  Idempotent — and a
        DUPLICATE call BLOCKS until the first finishes: the CLI's
        SIGTERM drain runs on a daemon thread, and main()'s
        finally-shutdown must not let the interpreter exit (killing
        every daemon session thread mid-request) while that drain is
        still completing admitted work."""
        with self._close_lock:
            already = self._closed
            self._closed = True
        if already:
            # No timeout: every teardown phase is itself bounded (drain
            # deadline, per-join escalation windows), so the first call
            # always terminates — while a guessed timeout here could
            # elapse before a long drain finishes and let the
            # interpreter exit, killing daemon session threads
            # mid-request.
            self._teardown_done.wait()
            return
        try:
            self._shutdown_impl(drain, drain_deadline_s)
        finally:
            self._teardown_done.set()

    def _shutdown_impl(self, drain: bool,
                       drain_deadline_s: Optional[float]) -> None:
        if drain:
            # Readiness flips FIRST, and the listener stays up for the
            # whole drain window shedding BUSY{"reason":"draining"}: a
            # balancer needs real time to observe the 503 and stop
            # routing, and every connection that races in during that
            # propagation window must get the structured shed frame —
            # closing the listener immediately would turn them into
            # ECONNREFUSED, the unstructured refusal drain exists to
            # prevent.
            self._server.draining = True
            metrics().gauge_set("service_draining", 1)
            budget = (drain_deadline_s if drain_deadline_s is not None
                      else self.limits.drain_deadline_s)
            drained = self._await_sessions(budget)
        # BaseServer.shutdown() waits on an event only a running
        # serve_forever loop sets; calling it before start() blocks forever.
        if self._serving:
            self._server.shutdown()
        self._server.server_close()
        if drain:
            if not drained:
                self._force_close_sessions("drain_deadline", count=True)
        else:
            self._force_close_sessions("shutdown", count=False)
        self._join_sessions()
        # After the session join: queued coalescer entries belong to
        # admitted sessions, so by now the lanes are empty on a graceful
        # drain — shutdown() only has live work to fail when sessions
        # were force-closed past the drain deadline.
        if self._server.coalescer is not None:
            self._server.coalescer.shutdown()
        if self._server.prewarmer is not None:
            self._server.prewarmer.shutdown()
        if drain:
            # The drain is over (documented: "1 WHILE a graceful drain is
            # in progress") — a later service in this process must not
            # inherit a stuck-at-1 gauge.
            metrics().gauge_set("service_draining", 0)
        if self._metrics is not None:
            self._metrics.shutdown()
        if self._stats_logger is not None:
            self._stats_logger.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                note_teardown(
                    LOG, "service_teardown_errors_total", "server_join",
                    "service accept-loop thread outlived its 5 s join",
                )

    def __enter__(self) -> "ParseService":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class ParseServiceClient:
    """Python reference client (the wire protocol is the interop surface;
    a JVM/Go client implements the same five-line framing).

    Retry behavior (round 12, all OFF by default so the default client
    stays byte-exact v1):

    - ``connect_retries``: reconnect attempts on a refused/failed
      connect, with exponential backoff + full jitter.
    - ``busy_retries``: :meth:`parse` retries after a structured ``BUSY``
      response, honoring the server's retry-after hint as the backoff
      floor.  Session-level sheds (reason ``sessions``/``draining``/
      ``sidecar_failover``) reconnect first — the server closed that
      connection by contract; behind a front tier the reconnect is what
      lands the session on a LIVE sidecar (docs/SERVICE.md "Fleet").
    - ``max_redirect_retries``: per-:meth:`parse` bound on those
      connection-level sheds specifically — a DYING fleet (every
      reconnect shed again) fails fast with
      :class:`ServiceUnavailableError` instead of burning the whole
      (possibly large) ``busy_retries`` budget on reconnect loops.
    - ``tenant``: optional tenant identity carried in the CONFIG frame
      (the front tier's fairness quotas key on it; a plain sidecar
      ignores it).
    - ``timeout``: socket timeout for connect/send/recv (None = block).
    """

    def __init__(
        self,
        host: str,
        port: int,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
        stats: bool = False,
        feeder_workers: Optional[int] = None,
        connect_retries: int = 0,
        busy_retries: int = 0,
        max_redirect_retries: int = 8,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        timeout: Optional[float] = None,
        tenant: Optional[str] = None,
        aggregate: Optional[Any] = None,
        traceparent: Optional[str] = None,
    ):
        self._addr = (host, port)
        self._stats = bool(stats)
        self._connect_retries = int(connect_retries)
        self._busy_retries = int(busy_retries)
        self._max_redirect_retries = int(max_redirect_retries)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._timeout = timeout
        #: Decoded STATS frame of the most recent parse() (stats sessions).
        self.last_stats: Optional[Dict[str, Any]] = None
        #: BUSY responses absorbed by retries (diagnosis/loadgen counter).
        self.busy_seen = 0
        config = {
            "log_format": log_format,
            "fields": list(fields),
            "timestamp_format": timestamp_format,
        }
        if feeder_workers:
            # Optional sharded-feeder framing for big batches
            # (docs/FEEDER.md); a v1 server ignores unknown keys.
            config["feeder_workers"] = int(feeder_workers)
        if tenant:
            # Tenant identity for the front tier's fairness quotas
            # (docs/SERVICE.md "Fleet"); a plain sidecar ignores it —
            # it is not part of the parser cache key.
            config["tenant"] = str(tenant)
        if stats:
            # Only stats sessions carry the key: a v1 server ignores it,
            # but omitting it keeps this client byte-exact v1 by default.
            config["stats"] = True
        if traceparent:
            # Distributed tracing head (PROTOCOL.md "traceparent"): the
            # session's requests join this trace.  A v1 server ignores
            # it; omitted, the CONFIG stays byte-exact v1.
            config["traceparent"] = str(traceparent)
        self._agg_spec = None
        if aggregate is not None:
            # Analytics pushdown (PROTOCOL.md "aggregate"): the session's
            # responses become aggregate frames; :meth:`parse` returns an
            # :class:`~logparser_tpu.analytics.AggregateState` instead of
            # a row table.  Parsed eagerly so a malformed spec fails at
            # construction, not as a server error frame.
            from .analytics.spec import parse_aggregate_config

            self._agg_spec = parse_aggregate_config(aggregate)
            config["aggregate"] = [op.as_dict()
                                   for op in self._agg_spec.ops]
        self._config_payload = json.dumps(config).encode("utf-8")
        self._sock = self._connect()

    # -- connection management ------------------------------------------

    def _connect(self) -> socket.socket:
        last: Optional[BaseException] = None
        for attempt in range(self._connect_retries + 1):
            sock: Optional[socket.socket] = None
            try:
                sock = socket.create_connection(
                    self._addr, timeout=self._timeout
                )
                sock.settimeout(self._timeout)
                write_frame(sock, self._config_payload)
                return sock
            except OSError as e:
                # A connect that made it to a socket but failed the
                # CONFIG write must not leak its fd across retries.
                if sock is not None:
                    sock.close()
                last = e
                if attempt >= self._connect_retries:
                    break
                self._backoff_sleep(attempt)
        assert last is not None
        raise last

    def _reconnect(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = self._connect()

    def _backoff_sleep(self, attempt: int, floor_s: float = 0.0) -> None:
        """Exponential backoff with full jitter (and the server's
        retry-after hint as the floor): synchronized client herds must
        decorrelate, or every retry wave lands as one thundering herd."""
        ceiling = min(self._backoff_max_s,
                      self._backoff_base_s * (2 ** attempt))
        delay = random.uniform(0.0, ceiling)
        time.sleep(max(floor_s, delay))

    # -- requests --------------------------------------------------------

    def parse(self, lines: Sequence[Union[str, bytes]]):
        """Ship one batch; returns a pyarrow.Table.  On a stats session
        the trailing STATS frame is decoded into :attr:`last_stats`.
        With ``busy_retries`` set, structured BUSY responses are
        retried with backoff instead of raised."""
        encoded = [
            line.encode("utf-8") if isinstance(line, str) else line
            for line in lines
        ]
        for line in encoded:
            if b"\n" in line:
                raise ValueError(
                    "loglines cannot contain '\\n'; split them before parse()"
                )
        payload = struct.pack(">I", len(encoded)) + b"\n".join(encoded)
        redirects = 0
        for attempt in range(self._busy_retries + 1):
            try:
                return self._roundtrip(payload)
            except ServiceBusyError as e:
                self.busy_seen += 1
                if attempt >= self._busy_retries:
                    raise
                if e.reason in RECONNECT_BUSY_REASONS:
                    # Connection-level shed: the server closed this
                    # socket by contract — reconnect (after honoring
                    # the retry hint) before retrying.  A separate,
                    # tighter budget bounds these: a fleet where EVERY
                    # reconnect sheds again (rolling restart gone bad,
                    # cascading sidecar failures) must fail fast, not
                    # spin through busy_retries reconnect cycles.
                    redirects += 1
                    if redirects > self._max_redirect_retries:
                        raise ServiceUnavailableError(
                            f"{redirects} consecutive connection-level "
                            f"sheds (last: {e.reason!r}) — service "
                            "unavailable"
                        ) from e
                    self._backoff_sleep(attempt, floor_s=e.retry_after_s)
                    self._reconnect()
                else:
                    self._backoff_sleep(attempt, floor_s=e.retry_after_s)
        raise AssertionError("unreachable")  # pragma: no cover

    def _roundtrip(self, payload: bytes):
        import pyarrow as pa

        write_frame(self._sock, payload)
        response = read_frame(self._sock)
        if response is None:
            raise ServiceClosedError("server closed the connection")
        with pa.ipc.open_stream(pa.BufferReader(response)) as reader:
            table = reader.read_all()
        if self._agg_spec is not None:
            from .analytics.state import AggregateState

            table = AggregateState.from_arrow(table, self._agg_spec)
        if self._stats:
            stats_frame = read_frame(self._sock)
            if stats_frame is None:
                raise ServiceClosedError(
                    "server closed the connection before the STATS frame"
                )
            self.last_stats = json.loads(stats_frame)
        return table

    def close(self) -> None:
        try:
            self._sock.sendall(struct.pack(">I", 0))
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "ParseServiceClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------------------
# CLI: run the sidecar standalone with telemetry wired up
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m logparser_tpu.service``: serve the sidecar protocol,
    optionally with a Prometheus /metrics (+ /healthz, /readyz) endpoint
    and periodic stats logging.  SIGTERM triggers a graceful drain
    (docs/SERVICE.md).  Env fallbacks: LOGPARSER_TPU_METRICS_PORT,
    LOGPARSER_TPU_STATS_INTERVAL, LOGPARSER_TPU_MAX_SESSIONS,
    LOGPARSER_TPU_REQUEST_DEADLINE, LOGPARSER_TPU_DRAIN_DEADLINE."""
    import argparse
    import os
    import signal

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="listen port (default 8123; --sidecar defaults "
                         "to 0 = ephemeral)")
    ap.add_argument(
        "--sidecar", action="store_true",
        help="supervised-sidecar run mode (docs/SERVICE.md \"Fleet\"): "
             "bind ephemeral service + metrics ports and print one "
             "machine-readable SIDECAR_READY JSON line on stdout so a "
             "front tier (logparser_tpu/front.py) can adopt, health-"
             "probe, and route to this process",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="Prometheus /metrics HTTP port (0 = ephemeral; omit to "
             "disable; env fallback LOGPARSER_TPU_METRICS_PORT — "
             "ignored under --sidecar, where every fleet member must "
             "bind its own ephemeral port)",
    )
    ap.add_argument(
        "--stats-interval", type=float,
        default=_env_float("LOGPARSER_TPU_STATS_INTERVAL"),
        help="seconds between one-line telemetry summaries (omit to disable)",
    )
    ap.add_argument(
        "--max-sessions", type=int,
        default=_env_int("LOGPARSER_TPU_MAX_SESSIONS") or 64,
        help="admitted-session budget; over it, connections shed BUSY",
    )
    ap.add_argument(
        "--max-inflight", type=int, default=0,
        help="concurrent parsing requests (0 = same as --max-sessions)",
    )
    ap.add_argument(
        "--request-deadline", type=float,
        default=_env_float("LOGPARSER_TPU_REQUEST_DEADLINE"),
        help="per-request parse deadline in seconds (omit to disable)",
    )
    ap.add_argument(
        "--idle-timeout", type=float, default=600.0,
        help="per-session idle window between frames, seconds (0 disables)",
    )
    ap.add_argument(
        "--frame-timeout", type=float, default=30.0,
        help="mid-frame transfer stall window, seconds (0 disables)",
    )
    ap.add_argument(
        "--drain-deadline", type=float,
        default=_env_float("LOGPARSER_TPU_DRAIN_DEADLINE") or 10.0,
        help="graceful-drain budget before force-close escalation, seconds",
    )
    ap.add_argument(
        "--no-coalesce", action="store_true",
        help="disable cross-session continuous batching (also "
             "LOGPARSER_TPU_COALESCE=0)",
    )
    ap.add_argument(
        "--coalesce-window-ms", type=float,
        default=_env_float("LOGPARSER_TPU_COALESCE_WINDOW_MS"),
        help="how long a forming shared batch waits for more sessions "
             "(default 2 ms; only paid when >1 session is live)",
    )
    ap.add_argument(
        "--coalesce-max-lines", type=int,
        default=_env_int("LOGPARSER_TPU_COALESCE_MAX_LINES"),
        help="shared device batch geometry ceiling in lines (default 4096)",
    )
    ap.add_argument(
        "--coalesce-queue-depth", type=int,
        default=_env_int("LOGPARSER_TPU_COALESCE_QUEUE_DEPTH"),
        help="bounded coalesce submission queue; full = structured "
             "BUSY{coalesce_queue} shed (default 256)",
    )
    ap.add_argument("--log-level", default=os.environ.get(
        "LOGPARSER_TPU_LOG_LEVEL", "INFO"))
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, str(args.log_level).upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    port = args.port if args.port is not None else (
        0 if args.sidecar else 8123)
    metrics_port = args.metrics_port
    if args.sidecar:
        # A sidecar without /readyz cannot be health-probed or drained
        # by the front tier: the metrics endpoint is mandatory — and
        # the env fallback is deliberately NOT consulted here (an
        # exported LOGPARSER_TPU_METRICS_PORT is inherited by every
        # spawned fleet member; a fixed port would EADDRINUSE all but
        # the first).  An explicit --metrics-port flag still wins.
        if metrics_port is None:
            metrics_port = 0
    elif metrics_port is None:
        metrics_port = _env_int("LOGPARSER_TPU_METRICS_PORT")
    svc = ParseService(
        args.host, port,
        metrics_port=metrics_port,
        stats_interval=args.stats_interval,
        max_sessions=args.max_sessions,
        max_inflight=args.max_inflight,
        request_deadline_s=args.request_deadline,
        idle_timeout_s=args.idle_timeout,
        frame_timeout_s=args.frame_timeout,
        drain_deadline_s=args.drain_deadline,
        coalesce=False if args.no_coalesce else None,
        coalesce_window_ms=args.coalesce_window_ms,
        coalesce_max_lines=args.coalesce_max_lines,
        coalesce_queue_depth=args.coalesce_queue_depth,
    )

    def _on_sigterm(signum, frame):  # noqa: ARG001 — signal contract
        # Flight dump FIRST: the drain may be escalated/killed, and the
        # last 60 s of silently-absorbed trouble must survive the
        # process (docs/OBSERVABILITY.md "Flight recorder").
        from .tracing import dump_flight

        flight_event("sigterm_drain",
                     drain_deadline_s=args.drain_deadline)
        dump_flight("sigterm")
        LOG.info("SIGTERM: draining (deadline %.1fs)", args.drain_deadline)
        threading.Thread(
            target=lambda: svc.shutdown(drain=True),
            name="logparser-tpu-drain", daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    # SIGUSR2 -> non-fatal flight dump; fatal faults dump via excepthook.
    from .tracing import (
        arm_flight_signals,
        install_flight_excepthook,
        sweep_flight_dumps,
    )

    sweep_flight_dumps()
    arm_flight_signals()
    install_flight_excepthook()
    LOG.info("parse service listening on %s:%d", svc.host, svc.port)
    if args.sidecar:
        # The adoption handshake (docs/SERVICE.md "Fleet"): exactly one
        # line, flushed, so the spawning front tier can read the bound
        # ephemeral ports without racing the listen() — both sockets
        # are already bound by construction above.
        print("SIDECAR_READY " + json.dumps({
            "port": svc.port,
            "metrics_port": svc.metrics_port,
            "pid": os.getpid(),
        }, sort_keys=True), flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        svc.shutdown()
    return 0


def _env_int(name: str) -> Optional[int]:
    import os

    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else None


def _env_float(name: str) -> Optional[float]:
    import os

    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else None


if __name__ == "__main__":  # pragma: no cover — CLI
    raise SystemExit(main())
