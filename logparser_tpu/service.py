"""Sidecar parse service: any-host interop over Arrow IPC.

SURVEY §7 step 5: "Java/any-host interop over Arrow IPC; sidecar service
mode".  The reference embeds the parser in-process in each engine (Hadoop,
Pig, Hive, ...); the TPU-native equivalent offers the same capability to
non-Python hosts by running the batch parser behind a socket: a JVM/Go/C++
data engine ships raw loglines to the sidecar and gets typed Arrow columns
back, so one TPU-attached process serves many engine workers.

Wire protocol (deliberately trivial to implement from any language):

    frame     := u32 big-endian length, then `length` payload bytes
    session   := CONFIG frame, then any number of
                 [LINES frame -> ARROW frame [-> STATS frame]]
    CONFIG    := JSON {"log_format": str, "fields": [str, ...],
                       "timestamp_format": str|null,
                       "assembly_workers": int|null (optional; host-side
                       Arrow assembly parallelism, default auto),
                       "feeder_workers": int|null (optional; >= 2 = frame
                       large LINES payloads through the sharded feeder
                       fabric — N threads frame disjoint byte-range shards
                       in parallel; the ARROW frame is unchanged in shape
                       and content, docs/FEEDER.md.  The fabric degrades,
                       never drops: a feeder failure re-parses the request
                       inline and demotes the session to inline parsing
                       for its remaining frames,
                       service_feeder_demotions_total),
                       "stats": bool (optional; true = one STATS JSON frame
                       after each ARROW frame — v1 sessions that omit the
                       key get byte-identical v1 behavior)}
    LINES     := u32 big-endian line count, then the loglines joined by '\n'
                 (UTF-8).  Loglines cannot contain '\n' — they are lines.
                 count=0 means an empty batch (an empty ARROW table comes
                 back); an empty logline is a present-but-empty row.
    ARROW     := one Arrow IPC stream (schema + one record batch) with the
                 requested columns plus the `__valid__` validity column
    STATS     := UTF-8 JSON telemetry frame (docs/PROTOCOL.md "stats" key):
                 per-request timing/sizes + process-cumulative stage
                 breakdown from the metrics registry
    error     := in place of an ARROW frame: 0xFFFFFFFF marker frame followed
                 by one frame of UTF-8 error text
    length 0  := end of session (client side); server closes the connection

Compiled parsers are cached per config, so successive sessions with the same
LogFormat skip recompilation (the service-side analogue of the reference's
"compile the Pattern only once", TokenFormatDissector.java:209-210).

Observability (docs/OBSERVABILITY.md): the service renders the process-wide
metrics registry as a Prometheus ``/metrics`` HTTP endpoint
(``metrics_port=``, or LOGPARSER_TPU_METRICS_PORT for the CLI) and can log a
periodic one-line stats summary (``stats_interval=`` /
LOGPARSER_TPU_STATS_INTERVAL).  ``python -m logparser_tpu.service`` runs the
sidecar standalone with both wired up.
"""
from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .observability import (
    log_version_banner_once,
    log_warning_once,
    metrics,
    suppressed_warning_counts,
)

LOG = logging.getLogger(__name__)

_ERROR_MARKER = 0xFFFFFFFF
_MAX_FRAME = 1 << 30  # 1 GiB sanity cap
# Sharded-feeder engagement floor: below this many lines a LINES frame is
# parsed inline — splitting pays for itself only when the framing work
# dwarfs the per-shard setup (docs/FEEDER.md "worker sizing").
_FEEDER_MIN_LINES = 4096


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    """One length-prefixed frame; None on clean EOF or length-0 frame."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length == 0:
        return None
    if length == _ERROR_MARKER:
        payload = read_frame(sock)
        raise ParseServiceError(
            (payload or b"(no error text)").decode("utf-8", errors="replace")
        )
    if length > _MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {_MAX_FRAME}")
    return _read_exact(sock, length)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls: no header+payload concatenation copy (Arrow responses
    # can be large).
    sock.sendall(struct.pack(">I", len(payload)))
    sock.sendall(payload)


def write_error(sock: socket.socket, message: str) -> None:
    sock.sendall(struct.pack(">I", _ERROR_MARKER))
    write_frame(sock, message.encode("utf-8"))


class ParseServiceError(RuntimeError):
    """Server-side failure relayed to the client."""


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _ParserCache:
    """LRU-bounded: each entry pins a compiled parser + XLA executables, so
    a long-lived sidecar serving many distinct configs must evict."""

    def __init__(self, max_entries: int = 32) -> None:
        self._lock = threading.Lock()
        self._max_entries = max_entries
        self._parsers: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._building: Dict[Tuple, threading.Lock] = {}

    def get(self, config: Dict[str, Any]):
        from .tpu.batch import TpuBatchParser

        key = (
            config["log_format"],
            tuple(config["fields"]),
            config.get("timestamp_format"),
            config.get("assembly_workers"),
        )
        # Compile outside the global lock: a cold compile takes seconds and
        # must not stall sessions whose parser is already cached.  A per-key
        # lock still deduplicates concurrent compiles of the same config.
        with self._lock:
            parser = self._parsers.get(key)
            if parser is not None:
                self._parsers.move_to_end(key)
                return parser
            key_lock = self._building.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:
                parser = self._parsers.get(key)
                if parser is not None:
                    self._parsers.move_to_end(key)
            if parser is None:
                try:
                    parser = TpuBatchParser(
                        config["log_format"],
                        list(config["fields"]),
                        timestamp_format=config.get("timestamp_format"),
                        # The wire delivers copy-mode Arrow only, so the
                        # parser never needs device view rows.
                        view_fields=(),
                        assembly_workers=config.get("assembly_workers"),
                    )
                    with self._lock:
                        self._parsers[key] = parser
                        while len(self._parsers) > self._max_entries:
                            self._parsers.popitem(last=False)
                finally:
                    # Failed builds must also drop the per-key build lock:
                    # the parser LRU is bounded but _building is not, and a
                    # long-lived sidecar fed many invalid configs would
                    # otherwise grow it without bound.
                    with self._lock:
                        self._building.pop(key, None)
            return parser


class _SessionHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # noqa: D102 — socketserver contract
        sock = self.request
        try:
            config_frame = read_frame(sock)
        except (ValueError, ConnectionError, ParseServiceError) as e:
            LOG.error("Bad config frame: %s", e)
            return
        if config_frame is None:
            return
        send_stats = False
        feeder_workers = 0
        try:
            config = json.loads(config_frame)
            # Optional telemetry opt-in (PROTOCOL.md "stats" CONFIG key):
            # absent/falsy = byte-identical v1 session.  Not part of the
            # parser cache key — it changes framing, not parsing.
            send_stats = bool(config.get("stats")) if isinstance(
                config, dict) else False
            # Optional sharded-feeder framing (docs/FEEDER.md): >= 2 =
            # big LINES payloads are framed by that many feeder threads
            # over byte-range shards.  Session behavior, not parser
            # state — not part of the cache key either.
            if isinstance(config, dict) and config.get("feeder_workers"):
                feeder_workers = int(config["feeder_workers"])
            parser = self.server.parser_cache.get(config)  # type: ignore[attr-defined]
            metrics().increment("service_sessions_total")
        except Exception as e:  # noqa: BLE001 — relay config errors to client
            # Keep draining the session instead of closing: a client already
            # mid-send of a large LINES frame would otherwise see ECONNRESET
            # and the RST can discard the buffered error text.
            message = f"bad config: {e}"
            try:
                write_error(sock, message)
                while read_frame(sock) is not None:
                    write_error(sock, message)
            except (OSError, ValueError, ParseServiceError):
                pass
            return

        while True:
            try:
                lines_frame = read_frame(sock)
            except (ValueError, ConnectionError, ParseServiceError) as e:
                LOG.error("Bad lines frame: %s", e)
                return
            if lines_frame is None:
                return  # end of session
            t_request = time.perf_counter()
            try:
                if len(lines_frame) < 4:
                    raise ValueError("LINES frame shorter than its count header")
                (count,) = struct.unpack(">I", lines_frame[:4])
                if count == 0 and len(lines_frame) > 4:
                    raise ValueError(
                        "LINES frame declared 0 lines but carries "
                        f"{len(lines_frame) - 4} payload bytes"
                    )
                blob = lines_frame[4:]
                n_lines = (blob.count(b"\n") + 1) if count else 0
                if n_lines != count:
                    raise ValueError(
                        f"LINES frame declared {count} lines, payload has "
                        f"{n_lines}"
                    )
                blob_shape = count and blob and not blob.endswith(b"\n") \
                    and b"\r" not in blob
                table = None
                if blob_shape and feeder_workers >= 2 \
                        and count >= _FEEDER_MIN_LINES:
                    # Sharded-feeder framing: the blob splits into
                    # byte-range shards framed by N threads in parallel;
                    # result tables concatenate back in corpus order
                    # (byte-identical to the inline blob path).
                    try:
                        table, oracle_rows, bad_lines = _feeder_parse(
                            parser, blob, count, feeder_workers
                        )
                        metrics().increment(
                            "service_feeder_requests_total")
                    except Exception as e:  # noqa: BLE001 — degrade, not drop
                        # ANY feeder-path failure demotes the SESSION:
                        # its remaining LINES frames parse inline (the
                        # fabric already self-heals worker crashes, so
                        # reaching here means even quarantine failed —
                        # don't re-enter it this session).
                        from .feeder import FeederError

                        feeder_workers = 0
                        metrics().increment(
                            "service_feeder_demotions_total")
                        log_warning_once(
                            LOG,
                            "service: sharded-feeder framing failed "
                            f"({type(e).__name__}); session demoted to "
                            "inline parsing",
                        )
                        if not isinstance(e, FeederError):
                            # A parse-shaped failure would fail inline
                            # too: relay it as a well-formed error frame
                            # (the session stays alive and its NEXT
                            # frame takes the inline path).
                            raise
                        # A fabric failure with intact input: retry THIS
                        # request inline below — the client sees an
                        # error-free ARROW stream, not a dropped
                        # connection or an error frame.
                        LOG.error("feeder fabric failed; request "
                                  "re-parsed inline: %s", e)
                if table is None:
                    if blob_shape:
                        # (an empty blob is one empty LINE per the
                        # protocol, which blob framing would drop —
                        # split path below)
                        # Common case: the payload IS the framer's input
                        # shape (no trailing newline, no carriage
                        # returns), so the blob ingest path applies — no
                        # Python line list.  emit_views=False: the wire
                        # ships copy-mode Arrow, so device view rows
                        # would be wasted kernel + D2H.
                        result = parser.parse_blob(blob, emit_views=False)
                    else:
                        result = parser.parse_batch(
                            blob.split(b"\n") if count else [],
                            emit_views=False,
                        )
                    # Copy mode for the wire: IPC does not dedupe shared
                    # buffers, so string_view columns would each ship a
                    # full copy of the batch buffer.
                    table = result.to_arrow(include_validity=True,
                                            strings="copy")
                    oracle_rows = result.oracle_rows
                    bad_lines = result.bad_lines
                from .tpu.arrow_bridge import table_to_ipc_bytes

                payload = table_to_ipc_bytes(table)
                write_frame(sock, payload)
                reg = metrics()
                dt = time.perf_counter() - t_request
                reg.increment("service_requests_total")
                reg.increment("service_lines_total", count)
                reg.observe("service_request_seconds", dt)
                if send_stats:
                    # STATS frame: per-request figures + the SAME
                    # process-cumulative stage breakdown /metrics and
                    # bench.py report (one metric definition everywhere).
                    stats = {
                        "v": 1,
                        "request": {
                            "lines": count,
                            "seconds": round(dt, 6),
                            "arrow_bytes": len(payload),
                            "oracle_lines": oracle_rows,
                            "bad_lines": bad_lines,
                        },
                        "stages": reg.stage_breakdown(),
                        # as_dict(): counters only — snapshot() would build
                        # every histogram's bucket view per request.
                        "counters": dict(sorted(reg.as_dict().items())),
                    }
                    write_frame(
                        sock,
                        json.dumps(stats, separators=(",", ":"),
                                   sort_keys=True).encode("utf-8"),
                    )
            except Exception as e:  # noqa: BLE001 — keep the session alive
                LOG.exception("parse failed")
                metrics().increment("service_request_errors_total")
                try:
                    write_error(sock, f"parse failed: {e}")
                except OSError:
                    return


def _feeder_parse(parser, blob: bytes, count: int, workers: int):
    """Parse one LINES blob through the sharded feeder fabric
    (docs/FEEDER.md): the payload splits into ``workers`` byte-range
    shards framed by feeder THREADS (a serving process must not fork,
    so the in-process ``inline`` hand-off applies — the shared-memory
    ring transport is for process pools), the parser consumes the
    encoded stream via ``parse_batch_stream`` (which also stages each
    next batch's H2D upload while the current one computes — the
    double-buffered device edge), and the per-batch tables concatenate
    back — in corpus order — into the single combined record batch the
    protocol promises.  Returns ``(table, oracle_rows, bad_lines)``."""
    import pyarrow as pa

    from .feeder import FeederPool, default_feeder_workers

    # The key is client-supplied: clamp to the host's own worker ceiling
    # so one CONFIG frame cannot spawn an arbitrary thread count.
    workers = max(2, min(workers, default_feeder_workers()))
    tables = []
    oracle_rows = 0
    bad_lines = 0
    with FeederPool(
        [blob],
        workers=workers,
        shard_bytes=max(1, -(-len(blob) // workers)),
        batch_lines=max(1024, -(-count // workers)),
        use_processes=False,
    ) as pool:
        for result in pool.feed(parser, emit_views=False):
            tables.append(
                result.to_arrow(include_validity=True, strings="copy")
            )
            oracle_rows += result.oracle_rows
            bad_lines += result.bad_lines
    return pa.concat_tables(tables).combine_chunks(), oracle_rows, bad_lines


class _MetricsHandler(BaseHTTPRequestHandler):
    """GET /metrics -> Prometheus text exposition of the process registry."""

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
        if path != "/metrics":
            self.send_error(404)
            return
        body = metrics().prometheus_text().encode("utf-8")
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        LOG.debug("metrics http: " + fmt, *args)


class MetricsEndpoint:
    """Standalone /metrics HTTP scrape endpoint (Prometheus text).  Owned
    by :class:`ParseService` when ``metrics_port`` is given; usable on its
    own for non-sidecar processes."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _MetricsHandler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MetricsEndpoint":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="logparser-tpu-metrics", daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        # Like ParseService.shutdown: BaseServer.shutdown() waits on an
        # event only a running serve_forever loop sets — never call it
        # for an endpoint that was constructed but not started.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class _StatsLogger:
    """Daemon thread logging a one-line telemetry summary every
    ``interval`` seconds: request/line counters, per-stage p99s, and
    suppressed-warning counts (the end-of-run summary CappedLogger/
    log_warning_once promise)."""

    def __init__(self, interval: float):
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="logparser-tpu-stats", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.log_once()

    @staticmethod
    def log_once() -> None:
        reg = metrics()
        snap = reg.snapshot()
        summary = {
            "counters": {
                k: v for k, v in snap["counters"].items()
                if not k.startswith("stage_items_total")
            },
            "stage_p99_ms": {
                stage: d["p99_ms"]
                for stage, d in reg.stage_breakdown().items()
            },
        }
        suppressed = suppressed_warning_counts()
        if suppressed:
            summary["suppressed_warnings"] = suppressed
        LOG.info("service stats: %s", json.dumps(summary, sort_keys=True))

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class ParseService:
    """The sidecar: `with ParseService() as svc: ... svc.port ...` or call
    `serve_forever()` from a main program.

    ``metrics_port`` (int, optional): also serve the process metrics
    registry as a Prometheus ``/metrics`` HTTP endpoint on that port
    (0 = ephemeral; read back via :attr:`metrics_port`).
    ``stats_interval`` (seconds, optional): log a one-line telemetry
    summary periodically at INFO level."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics_port: Optional[int] = None,
                 stats_interval: Optional[float] = None):
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _SessionHandler)
        self._server.parser_cache = _ParserCache()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._metrics: Optional[MetricsEndpoint] = None
        if metrics_port is not None:
            self._metrics = MetricsEndpoint(host, metrics_port)
        self._stats_logger: Optional[_StatsLogger] = None
        if stats_interval:
            self._stats_logger = _StatsLogger(float(stats_interval))

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def metrics_port(self) -> Optional[int]:
        """The bound /metrics HTTP port (None when not enabled)."""
        return self._metrics.port if self._metrics is not None else None

    def _start_sidecars(self) -> None:
        log_version_banner_once(LOG)
        if self._metrics is not None:
            self._metrics.start()
            LOG.info("serving /metrics on port %d", self._metrics.port)
        if self._stats_logger is not None:
            self._stats_logger.start()

    def start(self) -> "ParseService":
        self._serving = True
        self._start_sidecars()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="logparser-tpu-service",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._start_sidecars()
        self._server.serve_forever()

    def shutdown(self) -> None:
        # BaseServer.shutdown() waits on an event only a running
        # serve_forever loop sets; calling it before start() blocks forever.
        if self._serving:
            self._server.shutdown()
        self._server.server_close()
        if self._metrics is not None:
            self._metrics.shutdown()
        if self._stats_logger is not None:
            self._stats_logger.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ParseService":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class ParseServiceClient:
    """Python reference client (the wire protocol is the interop surface;
    a JVM/Go client implements the same five-line framing)."""

    def __init__(
        self,
        host: str,
        port: int,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
        stats: bool = False,
        feeder_workers: Optional[int] = None,
    ):
        self._sock = socket.create_connection((host, port))
        self._stats = bool(stats)
        #: Decoded STATS frame of the most recent parse() (stats sessions).
        self.last_stats: Optional[Dict[str, Any]] = None
        config = {
            "log_format": log_format,
            "fields": list(fields),
            "timestamp_format": timestamp_format,
        }
        if feeder_workers:
            # Optional sharded-feeder framing for big batches
            # (docs/FEEDER.md); a v1 server ignores unknown keys.
            config["feeder_workers"] = int(feeder_workers)
        if stats:
            # Only stats sessions carry the key: a v1 server ignores it,
            # but omitting it keeps this client byte-exact v1 by default.
            config["stats"] = True
        write_frame(self._sock, json.dumps(config).encode("utf-8"))

    def parse(self, lines: Sequence[Union[str, bytes]]):
        """Ship one batch; returns a pyarrow.Table.  On a stats session
        the trailing STATS frame is decoded into :attr:`last_stats`."""
        import pyarrow as pa

        encoded = [
            line.encode("utf-8") if isinstance(line, str) else line
            for line in lines
        ]
        for line in encoded:
            if b"\n" in line:
                raise ValueError(
                    "loglines cannot contain '\\n'; split them before parse()"
                )
        payload = struct.pack(">I", len(encoded)) + b"\n".join(encoded)
        write_frame(self._sock, payload)
        response = read_frame(self._sock)
        if response is None:
            raise ParseServiceError("server closed the connection")
        with pa.ipc.open_stream(pa.BufferReader(response)) as reader:
            table = reader.read_all()
        if self._stats:
            stats_frame = read_frame(self._sock)
            if stats_frame is None:
                raise ParseServiceError(
                    "server closed the connection before the STATS frame"
                )
            self.last_stats = json.loads(stats_frame)
        return table

    def close(self) -> None:
        try:
            self._sock.sendall(struct.pack(">I", 0))
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "ParseServiceClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------------------
# CLI: run the sidecar standalone with telemetry wired up
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m logparser_tpu.service``: serve the sidecar protocol,
    optionally with a Prometheus /metrics endpoint and periodic stats
    logging.  Env fallbacks: LOGPARSER_TPU_METRICS_PORT,
    LOGPARSER_TPU_STATS_INTERVAL."""
    import argparse
    import os

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8123)
    ap.add_argument(
        "--metrics-port", type=int,
        default=_env_int("LOGPARSER_TPU_METRICS_PORT"),
        help="Prometheus /metrics HTTP port (0 = ephemeral; omit to disable)",
    )
    ap.add_argument(
        "--stats-interval", type=float,
        default=_env_float("LOGPARSER_TPU_STATS_INTERVAL"),
        help="seconds between one-line telemetry summaries (omit to disable)",
    )
    ap.add_argument("--log-level", default=os.environ.get(
        "LOGPARSER_TPU_LOG_LEVEL", "INFO"))
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, str(args.log_level).upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    svc = ParseService(
        args.host, args.port,
        metrics_port=args.metrics_port,
        stats_interval=args.stats_interval,
    )
    LOG.info("parse service listening on %s:%d", svc.host, svc.port)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        svc.shutdown()
    return 0


def _env_int(name: str) -> Optional[int]:
    import os

    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else None


def _env_float(name: str) -> Optional[float]:
    import os

    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else None


if __name__ == "__main__":  # pragma: no cover — CLI
    raise SystemExit(main())
