"""Sidecar parse service: any-host interop over Arrow IPC.

SURVEY §7 step 5: "Java/any-host interop over Arrow IPC; sidecar service
mode".  The reference embeds the parser in-process in each engine (Hadoop,
Pig, Hive, ...); the TPU-native equivalent offers the same capability to
non-Python hosts by running the batch parser behind a socket: a JVM/Go/C++
data engine ships raw loglines to the sidecar and gets typed Arrow columns
back, so one TPU-attached process serves many engine workers.

Wire protocol (deliberately trivial to implement from any language):

    frame     := u32 big-endian length, then `length` payload bytes
    session   := CONFIG frame, then any number of [LINES frame -> ARROW frame]
    CONFIG    := JSON {"log_format": str, "fields": [str, ...],
                       "timestamp_format": str|null,
                       "assembly_workers": int|null (optional; host-side
                       Arrow assembly parallelism, default auto)}
    LINES     := u32 big-endian line count, then the loglines joined by '\n'
                 (UTF-8).  Loglines cannot contain '\n' — they are lines.
                 count=0 means an empty batch (an empty ARROW table comes
                 back); an empty logline is a present-but-empty row.
    ARROW     := one Arrow IPC stream (schema + one record batch) with the
                 requested columns plus the `__valid__` validity column
    error     := in place of an ARROW frame: 0xFFFFFFFF marker frame followed
                 by one frame of UTF-8 error text
    length 0  := end of session (client side); server closes the connection

Compiled parsers are cached per config, so successive sessions with the same
LogFormat skip recompilation (the service-side analogue of the reference's
"compile the Pattern only once", TokenFormatDissector.java:209-210).
"""
from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

LOG = logging.getLogger(__name__)

_ERROR_MARKER = 0xFFFFFFFF
_MAX_FRAME = 1 << 30  # 1 GiB sanity cap


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    """One length-prefixed frame; None on clean EOF or length-0 frame."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length == 0:
        return None
    if length == _ERROR_MARKER:
        payload = read_frame(sock)
        raise ParseServiceError(
            (payload or b"(no error text)").decode("utf-8", errors="replace")
        )
    if length > _MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap {_MAX_FRAME}")
    return _read_exact(sock, length)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls: no header+payload concatenation copy (Arrow responses
    # can be large).
    sock.sendall(struct.pack(">I", len(payload)))
    sock.sendall(payload)


def write_error(sock: socket.socket, message: str) -> None:
    sock.sendall(struct.pack(">I", _ERROR_MARKER))
    write_frame(sock, message.encode("utf-8"))


class ParseServiceError(RuntimeError):
    """Server-side failure relayed to the client."""


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _ParserCache:
    """LRU-bounded: each entry pins a compiled parser + XLA executables, so
    a long-lived sidecar serving many distinct configs must evict."""

    def __init__(self, max_entries: int = 32) -> None:
        self._lock = threading.Lock()
        self._max_entries = max_entries
        self._parsers: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._building: Dict[Tuple, threading.Lock] = {}

    def get(self, config: Dict[str, Any]):
        from .tpu.batch import TpuBatchParser

        key = (
            config["log_format"],
            tuple(config["fields"]),
            config.get("timestamp_format"),
            config.get("assembly_workers"),
        )
        # Compile outside the global lock: a cold compile takes seconds and
        # must not stall sessions whose parser is already cached.  A per-key
        # lock still deduplicates concurrent compiles of the same config.
        with self._lock:
            parser = self._parsers.get(key)
            if parser is not None:
                self._parsers.move_to_end(key)
                return parser
            key_lock = self._building.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:
                parser = self._parsers.get(key)
                if parser is not None:
                    self._parsers.move_to_end(key)
            if parser is None:
                try:
                    parser = TpuBatchParser(
                        config["log_format"],
                        list(config["fields"]),
                        timestamp_format=config.get("timestamp_format"),
                        # The wire delivers copy-mode Arrow only, so the
                        # parser never needs device view rows.
                        view_fields=(),
                        assembly_workers=config.get("assembly_workers"),
                    )
                    with self._lock:
                        self._parsers[key] = parser
                        while len(self._parsers) > self._max_entries:
                            self._parsers.popitem(last=False)
                finally:
                    # Failed builds must also drop the per-key build lock:
                    # the parser LRU is bounded but _building is not, and a
                    # long-lived sidecar fed many invalid configs would
                    # otherwise grow it without bound.
                    with self._lock:
                        self._building.pop(key, None)
            return parser


class _SessionHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # noqa: D102 — socketserver contract
        sock = self.request
        try:
            config_frame = read_frame(sock)
        except (ValueError, ConnectionError, ParseServiceError) as e:
            LOG.error("Bad config frame: %s", e)
            return
        if config_frame is None:
            return
        try:
            config = json.loads(config_frame)
            parser = self.server.parser_cache.get(config)  # type: ignore[attr-defined]
        except Exception as e:  # noqa: BLE001 — relay config errors to client
            # Keep draining the session instead of closing: a client already
            # mid-send of a large LINES frame would otherwise see ECONNRESET
            # and the RST can discard the buffered error text.
            message = f"bad config: {e}"
            try:
                write_error(sock, message)
                while read_frame(sock) is not None:
                    write_error(sock, message)
            except (OSError, ValueError, ParseServiceError):
                pass
            return

        while True:
            try:
                lines_frame = read_frame(sock)
            except (ValueError, ConnectionError, ParseServiceError) as e:
                LOG.error("Bad lines frame: %s", e)
                return
            if lines_frame is None:
                return  # end of session
            try:
                if len(lines_frame) < 4:
                    raise ValueError("LINES frame shorter than its count header")
                (count,) = struct.unpack(">I", lines_frame[:4])
                if count == 0 and len(lines_frame) > 4:
                    raise ValueError(
                        "LINES frame declared 0 lines but carries "
                        f"{len(lines_frame) - 4} payload bytes"
                    )
                blob = lines_frame[4:]
                n_lines = (blob.count(b"\n") + 1) if count else 0
                if n_lines != count:
                    raise ValueError(
                        f"LINES frame declared {count} lines, payload has "
                        f"{n_lines}"
                    )
                if count and blob and not blob.endswith(b"\n") \
                        and b"\r" not in blob:
                    # (an empty blob is one empty LINE per the protocol,
                    # which blob framing would drop — split path below)
                    # Common case: the payload IS the framer's input shape
                    # (no trailing newline, no carriage returns), so the
                    # blob ingest path applies — no Python line list.
                    # emit_views=False: the wire ships copy-mode Arrow,
                    # so device view rows would be wasted kernel + D2H.
                    result = parser.parse_blob(blob, emit_views=False)
                else:
                    result = parser.parse_batch(
                        blob.split(b"\n") if count else [],
                        emit_views=False,
                    )
                # Copy mode for the wire: IPC does not dedupe shared
                # buffers, so string_view columns would each ship a full
                # copy of the batch buffer.
                table = result.to_arrow(include_validity=True,
                                        strings="copy")
                import pyarrow as pa

                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, table.schema) as writer:
                    writer.write_table(table)
                write_frame(sock, sink.getvalue().to_pybytes())
            except Exception as e:  # noqa: BLE001 — keep the session alive
                LOG.exception("parse failed")
                try:
                    write_error(sock, f"parse failed: {e}")
                except OSError:
                    return


class ParseService:
    """The sidecar: `with ParseService() as svc: ... svc.port ...` or call
    `serve_forever()` from a main program."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _SessionHandler)
        self._server.parser_cache = _ParserCache()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._serving = False

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "ParseService":
        self._serving = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="logparser-tpu-service",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._server.serve_forever()

    def shutdown(self) -> None:
        # BaseServer.shutdown() waits on an event only a running
        # serve_forever loop sets; calling it before start() blocks forever.
        if self._serving:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ParseService":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class ParseServiceClient:
    """Python reference client (the wire protocol is the interop surface;
    a JVM/Go client implements the same five-line framing)."""

    def __init__(
        self,
        host: str,
        port: int,
        log_format: str,
        fields: Sequence[str],
        timestamp_format: Optional[str] = None,
    ):
        self._sock = socket.create_connection((host, port))
        config = {
            "log_format": log_format,
            "fields": list(fields),
            "timestamp_format": timestamp_format,
        }
        write_frame(self._sock, json.dumps(config).encode("utf-8"))

    def parse(self, lines: Sequence[Union[str, bytes]]):
        """Ship one batch; returns a pyarrow.Table."""
        import pyarrow as pa

        encoded = [
            line.encode("utf-8") if isinstance(line, str) else line
            for line in lines
        ]
        for line in encoded:
            if b"\n" in line:
                raise ValueError(
                    "loglines cannot contain '\\n'; split them before parse()"
                )
        payload = struct.pack(">I", len(encoded)) + b"\n".join(encoded)
        write_frame(self._sock, payload)
        response = read_frame(self._sock)
        if response is None:
            raise ParseServiceError("server closed the connection")
        with pa.ipc.open_stream(pa.BufferReader(response)) as reader:
            return reader.read_all()

    def close(self) -> None:
        try:
            self._sock.sendall(struct.pack(">I", 0))
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "ParseServiceClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
