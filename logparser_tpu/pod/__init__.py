"""Pod-scale parse fabric: multi-host, multi-chip batch jobs.

The composition layer ROADMAP direction 1 names (docs/JOBS.md "Pod
jobs"): :func:`run_pod` partitions a corpus's shard plan into disjoint
contiguous per-host ranges (``feeder.shards.shards_for_host``), runs one
supervised single-host job per pod host — each host's feeder ring feeds
its local chips, with the device parse optionally laid out data-parallel
over a ``jax.sharding.Mesh`` (``TpuBatchParser(data_parallel=N)``) —
and folds the per-host commit logs into one merged manifest
(:func:`~logparser_tpu.jobs.manifest.merge_manifests`), after which the
pod directory is indistinguishable from a single-host job's: same
files, same ``merged_hash``, same resume semantics.  A dead host's
range is just a run of uncommitted shards; relaunching (or resuming)
re-parses exactly that run and nothing else.

CLI: ``python -m logparser_tpu.pod`` (simulated pod: every host a local
subprocess) or ``python -m logparser_tpu.jobs --hosts N --host-index i``
per real host, plus ``--merge-only`` once all hosts report complete.
"""
from .runner import (  # noqa: F401
    HostResult,
    PodPolicy,
    PodReport,
    PodSpec,
    run_pod,
)
