"""Pod job runner: N per-host jobs + manifest merge (docs/JOBS.md).

``run_pod(PodSpec(...))`` drives one pod-level job:

1. the GLOBAL shard plan is computed once (``feeder/shards.py`` — the
   same plan every host computes independently from the same spec, so
   no plan ever travels over a wire);
2. each host runs its contiguous disjoint slice of that plan as an
   ordinary single-host job (``jobs/runner.py`` with
   ``n_hosts``/``host_index`` set), committing into its per-host
   manifest — subprocesses by default (the simulated-pod shape: real
   deployments run the same CLI on real hosts against a shared
   filesystem), or inline in-process for tests and the bench;
3. a host that dies or fails is relaunched up to
   ``PodPolicy.host_retries`` times — resume semantics make this free
   (its committed shards are skipped; only the uncommitted tail of its
   range replays);
4. the per-host manifests merge into the top-level ``manifest.json``
   (fingerprint-checked, duplicate-commit-checked), leaving a directory
   byte-indistinguishable from a single-host run over the same spec.

The kill-drill invariant, one level up from the single-host one: SIGKILL
any host mid-job, rerun ``run_pod`` (or resume the one host), and the
merged output is byte-identical to an undisturbed single-host run, with
committed shards never re-parsed — drilled live in
``tools/pod_smoke.py`` and gated in bench's ``pod`` section.
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..feeder.shards import (
    DEFAULT_SHARD_BYTES,
    SourceT,
    normalize_sources,
    plan_shards,
)
from ..jobs.manifest import ManifestError, host_manifest_name, merge_manifests
from ..jobs.runner import (
    DEFAULT_JOB_BATCH_LINES,
    EXIT_PREEMPTED,
    JobPolicy,
    JobSpec,
    run_job,
)
from ..observability import metrics

LOG = logging.getLogger(__name__)


@dataclass
class PodSpec:
    """One pod job: the output-determining geometry (identical to
    :class:`~logparser_tpu.jobs.runner.JobSpec`'s — n_hosts is
    EXECUTION-only, which is what makes an N-host merge byte-comparable
    to a 1-host run) plus pod execution knobs."""

    sources: Sequence[SourceT]
    log_format: str
    fields: Sequence[str]
    out_dir: str
    n_hosts: int = 2
    shard_bytes: int = DEFAULT_SHARD_BYTES
    batch_lines: int = DEFAULT_JOB_BATCH_LINES
    # Analytics pushdown (docs/ANALYTICS.md): aggregate-mode pod — each
    # host lands partial-aggregate sidecars, the merge step folds them
    # into the pod-level answer.  Output-determining (fingerprinted by
    # every host job).
    aggregate: Optional[Any] = None
    # Execution-only:
    workers: Optional[int] = None          # feeder workers per host
    use_processes: Optional[bool] = None
    transport: Optional[str] = None
    data_parallel: Optional[int] = None    # chips per host (mesh DP)
    host_env: Optional[Dict[str, str]] = None  # extra env per subprocess
    # Persistent compile cache (docs/COMPILE.md) — execution-only: cached
    # executables change when work starts, never what it produces.
    compile_cache: Optional[str] = None

    def host_job_spec(self, host_index: int) -> JobSpec:
        return JobSpec(
            sources=list(self.sources),
            log_format=self.log_format,
            fields=list(self.fields),
            out_dir=self.out_dir,
            shard_bytes=self.shard_bytes,
            batch_lines=self.batch_lines,
            workers=self.workers,
            use_processes=self.use_processes,
            transport=self.transport,
            n_hosts=self.n_hosts,
            host_index=host_index,
            data_parallel=self.data_parallel,
            aggregate=self.aggregate,
        )


@dataclass
class PodPolicy:
    """Pod runner tunables."""

    host_retries: int = 1        # relaunches per dead/failed host
    host_timeout_s: float = 3600.0
    io_retries: int = 3          # per-host writer retry ladder
    inline: bool = False         # run hosts sequentially in-process
    merge: bool = True           # merge manifests after the host wave


@dataclass
class HostResult:
    """One host's outcome across its launches."""

    host_index: int
    launches: int = 0
    returncode: Optional[int] = None
    report: Optional[Dict[str, Any]] = None  # the host job's as_dict()
    error: Optional[str] = None
    preempted: bool = False      # a launch exited EXIT_PREEMPTED

    @property
    def ok(self) -> bool:
        return (self.returncode == 0 and self.report is not None
                and self.report.get("complete", False))


@dataclass
class PodReport:
    """What one ``run_pod`` call did."""

    out_dir: str
    n_hosts: int
    shards_total: int = 0
    merged_shards: int = 0
    hosts: List[HostResult] = field(default_factory=list)
    wall_s: float = 0.0
    merge_error: Optional[str] = None
    # Aggregate-mode pods: the merged job-level aggregate summary
    # (None for row pods or before a successful merge).
    aggregate: Optional[List[Dict[str, Any]]] = None

    @property
    def complete(self) -> bool:
        return (self.merge_error is None
                and self.merged_shards == self.shards_total
                and all(h.ok for h in self.hosts))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "out_dir": self.out_dir,
            "n_hosts": self.n_hosts,
            "shards_total": self.shards_total,
            "merged_shards": self.merged_shards,
            "complete": self.complete,
            "wall_s": round(self.wall_s, 4),
            **({"merge_error": self.merge_error}
               if self.merge_error else {}),
            **({"aggregate": self.aggregate}
               if self.aggregate is not None else {}),
            "hosts": [
                {
                    "host": h.host_index,
                    "launches": h.launches,
                    "returncode": h.returncode,
                    "ok": h.ok,
                    **({"preempted": True} if h.preempted else {}),
                    **({"error": h.error} if h.error else {}),
                    **({"committed": h.report.get("committed"),
                        "skipped": h.report.get("skipped"),
                        "rejects": h.report.get("rejects")}
                       if h.report else {}),
                }
                for h in self.hosts
            ],
        }


def host_argv(spec: PodSpec, host_index: int,
              policy: PodPolicy) -> List[str]:
    """The per-host CLI line — exactly what an operator runs on each
    real host of a shared-filesystem pod (the subprocess path and the
    documentation are the same command)."""
    argv = [sys.executable, "-m", "logparser_tpu.jobs",
            *[os.fspath(s) for s in spec.sources],
            "--format", spec.log_format,
            "--out", spec.out_dir,
            "--shard-bytes", str(spec.shard_bytes),
            "--batch-lines", str(spec.batch_lines),
            "--hosts", str(spec.n_hosts),
            "--host-index", str(host_index),
            "--io-retries", str(policy.io_retries)]
    for f in spec.fields:
        argv += ["--field", f]
    if spec.workers:
        argv += ["--workers", str(spec.workers)]
    if spec.use_processes is False:
        argv += ["--threads"]
    if spec.transport:
        argv += ["--transport", spec.transport]
    if spec.data_parallel:
        argv += ["--data-parallel", str(spec.data_parallel)]
    if spec.compile_cache:
        argv += ["--compile-cache", spec.compile_cache]
    if spec.aggregate is not None:
        # Canonical JSON on the wire: every host must fingerprint the
        # IDENTICAL spec string or the merge would refuse its manifests.
        from ..analytics.spec import parse_aggregate_config

        argv += ["--aggregate",
                 parse_aggregate_config(spec.aggregate).canonical_key()]
    return argv


def _launch_host(spec: PodSpec, host_index: int, policy: PodPolicy,
                 traceparent: Optional[str] = None) -> subprocess.Popen:
    env = dict(os.environ)
    if spec.host_env:
        env.update(spec.host_env)
    if traceparent:
        # The host's job_run root span parents under this pod's trace;
        # the env var is the cross-process carrier (docs/OBSERVABILITY.md
        # "Tracing").
        env["LOGPARSER_TPU_TRACEPARENT"] = traceparent
    return subprocess.Popen(
        host_argv(spec, host_index, policy),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, text=True, start_new_session=True,
    )


def _committed_in_host_manifest(out_dir: str, host_index: int) -> int:
    """Committed-shard count per the host's on-disk commit log."""
    from ..jobs.manifest import count_committed_shards

    return count_committed_shards(out_dir, host_manifest_name(host_index))


def _preemption_watcher(out_dir: str, host_index: int, after: int,
                        proc: subprocess.Popen,
                        poll_s: float = 0.05) -> None:
    """The ``preempt_host`` chaos drill: SIGTERM the host's jobs CLI
    once its commit log holds ``after`` shards — the CLI must finish
    the shard boundary in flight and exit EXIT_PREEMPTED, and the
    relaunch must resume with zero re-parsed shards (docs/JOBS.md
    "Preemption")."""
    while proc.poll() is None:
        if _committed_in_host_manifest(out_dir, host_index) >= after:
            try:
                proc.terminate()
            except OSError:
                pass
            return
        time.sleep(poll_s)


def _host_report_from_stdout(text: str) -> Optional[Dict[str, Any]]:
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                return None
    return None


def _run_host_inline(spec: PodSpec, host_index: int,
                     policy: PodPolicy, parser: Any) -> HostResult:
    hr = HostResult(host_index=host_index, launches=1)
    try:
        report = run_job(
            spec.host_job_spec(host_index), parser=parser,
            policy=JobPolicy(io_retries=policy.io_retries),
        )
        hr.report = report.as_dict()
        hr.returncode = 0 if not report.failed else 1
    except (ManifestError, ValueError) as e:
        hr.returncode = 2
        hr.error = str(e)
    return hr


def run_pod(spec: PodSpec, policy: Optional[PodPolicy] = None,
            parser: Any = None, chaos: Any = None) -> PodReport:
    """Run (or resume) one pod job end to end: host wave, bounded
    relaunch of dead/failed/preempted hosts, manifest merge.  ``parser``
    is only legal inline (subprocess hosts build their own).  ``chaos``
    arms pod-tier fault injection (``preempt_host`` — subprocess mode
    only; ChaosSpec / grammar string, default the LOGPARSER_TPU_CHAOS
    env var); see module docstring."""
    policy = policy or PodPolicy()
    if spec.n_hosts < 1:
        raise ValueError(f"n_hosts must be positive, got {spec.n_hosts}")
    from ..tools.chaos import ChaosSpec, PodChaos

    if chaos is None:
        chaos_spec = ChaosSpec.from_env()
    elif isinstance(chaos, str):
        chaos_spec = ChaosSpec.parse(chaos)
    else:
        chaos_spec = chaos
    pod_chaos = PodChaos(chaos_spec) if chaos_spec is not None else None
    # host -> committed-shard trigger; popped as each fires (once per
    # pod run, so the relaunch completes clean — the recovery drill).
    preempt_plan = pod_chaos.preempt_plan() if pod_chaos else {}
    t0 = time.perf_counter()
    reg = metrics()
    reg.increment("pod_runs_total")
    from ..tracing import child_span, root_span

    pod_span = root_span(
        "pod_run",
        traceparent=os.environ.get("LOGPARSER_TPU_TRACEPARENT"),
        attrs={"hosts": spec.n_hosts},
    )
    pod_ctx = pod_span.context if pod_span is not None else None
    plan = plan_shards(normalize_sources(spec.sources), spec.shard_bytes)
    report = PodReport(out_dir=spec.out_dir, n_hosts=spec.n_hosts,
                       shards_total=len(plan))
    results = [HostResult(host_index=i) for i in range(spec.n_hosts)]
    report.hosts = results

    if policy.inline:
        for i in range(spec.n_hosts):
            h_span = child_span("pod_host_launch", pod_ctx,
                                attrs={"host": i, "inline": True})
            hr = _run_host_inline(spec, i, policy, parser)
            # Each failed LAUNCH counts once; a config refusal (rc 2)
            # never retries — resuming it would refuse identically.
            while (not hr.ok and hr.returncode != 2
                   and hr.launches <= policy.host_retries):
                reg.increment("pod_host_failures_total")
                retry = _run_host_inline(spec, i, policy, parser)
                retry.launches = hr.launches + 1
                hr = retry
            if not hr.ok:
                reg.increment("pod_host_failures_total")
            if h_span is not None:
                h_span.end(returncode=hr.returncode,
                           launches=hr.launches)
            results[i] = hr
    else:
        if parser is not None:
            raise ValueError("parser reuse requires PodPolicy(inline=True)")
        pending = list(range(spec.n_hosts))
        attempt = 0
        while pending and attempt <= policy.host_retries:
            procs = {}
            host_spans = {}
            for i in pending:
                results[i].launches += 1
                reg.increment("pod_hosts_launched_total")
                h_span = child_span(
                    "pod_host_launch", pod_ctx,
                    attrs={"host": i, "attempt": attempt})
                host_spans[i] = h_span
                procs[i] = _launch_host(
                    spec, i, policy,
                    traceparent=(h_span.traceparent
                                 if h_span is not None else None))
                after = preempt_plan.pop(i, None)
                if after is not None:
                    threading.Thread(
                        target=_preemption_watcher,
                        args=(spec.out_dir, i, after, procs[i]),
                        name=f"pod-preempt-{i}", daemon=True,
                    ).start()
            reg.gauge_set("pod_hosts_alive", len(procs))
            deadline = time.monotonic() + policy.host_timeout_s
            for i, p in procs.items():
                budget = max(0.0, deadline - time.monotonic())
                try:
                    out, _ = p.communicate(timeout=budget)
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                    results[i].error = (
                        f"host {i} exceeded its "
                        f"{policy.host_timeout_s:.0f}s budget (killed)"
                    )
                results[i].returncode = p.returncode
                results[i].report = _host_report_from_stdout(out)
                if host_spans.get(i) is not None:
                    host_spans[i].end(returncode=p.returncode)
                reg.gauge_set(
                    "pod_hosts_alive",
                    sum(1 for q in procs.values() if q.poll() is None),
                )
            failed = [i for i in pending if not results[i].ok
                      and results[i].returncode != 2]
            for i in failed:
                if results[i].returncode == EXIT_PREEMPTED:
                    # The clean preemption exit: the host honored
                    # SIGTERM at a commit boundary — a resume is free
                    # (zero re-parsed shards), so a relaunch is the
                    # whole recovery.
                    results[i].preempted = True
                    reg.increment("pod_host_preemptions_total")
                    LOG.warning(
                        "pod: host %d preempted (clean SIGTERM exit)%s",
                        i,
                        " — relaunching (resume re-parses zero "
                        "committed shards)"
                        if attempt < policy.host_retries else "",
                    )
                    continue
                reg.increment("pod_host_failures_total")
                LOG.warning("pod: host %d failed (rc=%s)%s", i,
                            results[i].returncode,
                            " — relaunching (resume skips its committed "
                            "shards)" if attempt < policy.host_retries
                            else "")
            pending = failed
            attempt += 1
        reg.gauge_set("pod_hosts_alive", 0)

    if policy.merge:
        try:
            merged = merge_manifests(spec.out_dir)
            report.merged_shards = len(merged.shards)
            reg.increment("pod_merge_runs_total")
            reg.increment("pod_merged_shards_total", len(merged.shards))
            if spec.aggregate is not None:
                # Pod-level aggregate: fold every committed shard's
                # partial sidecar — hosts merge exactly like manifests
                # (docs/ANALYTICS.md), and the answer over a partial
                # merge is the partial answer, never a wrong one.
                from ..jobs.writer import merged_job_aggregate

                t_m = time.perf_counter()
                report.aggregate = merged_job_aggregate(
                    spec.out_dir, merged).summary()
                reg.observe("analytics_partial_merge_seconds",
                            time.perf_counter() - t_m)
        except (ManifestError, ValueError, OSError) as e:
            report.merge_error = str(e)
            reg.increment("pod_merge_refusals_total")
    report.wall_s = time.perf_counter() - t0
    if pod_span is not None:
        pod_span.end(merged_shards=report.merged_shards,
                     wall_s=round(report.wall_s, 3))
    return report
