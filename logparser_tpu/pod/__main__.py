"""CLI for the pod job runner: ``python -m logparser_tpu.pod``.

Runs an N-host pod job on THIS machine (each host a subprocess of the
single-host jobs CLI — the simulated-pod shape; on a real pod run the
printed per-host command on each host instead) and merges the per-host
manifests.  Resumable exactly like the single-host CLI: rerunning the
same command after any crash/kill skips every committed shard.

Example::

    python -m logparser_tpu.pod access.log \\
        --format '%h %l %u %t "%r" %>s %b' \\
        --field IP:connection.client.host \\
        --field STRING:request.status.last \\
        --out /data/podjob --hosts 2

Exit codes: 0 = pod complete (all shards merged); 1 = one or more
hosts/shards failed (rerun resumes them); 2 = configuration error.
"""
from __future__ import annotations

import argparse
import json
import shlex
import sys

from ..feeder.shards import DEFAULT_SHARD_BYTES
from ..jobs.manifest import ManifestError
from ..jobs.runner import DEFAULT_JOB_BATCH_LINES
from .runner import PodPolicy, PodSpec, host_argv, run_pod


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m logparser_tpu.pod",
        description="Pod-scale corpus -> sharded-Arrow parse job "
                    "(docs/JOBS.md 'Pod jobs')",
    )
    ap.add_argument("sources", nargs="+",
                    help="input log files, in corpus order")
    ap.add_argument("--format", required=True, dest="log_format")
    ap.add_argument("--field", action="append", required=True,
                    dest="fields", metavar="TYPE:path")
    ap.add_argument("--out", required=True, dest="out_dir")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--shard-bytes", type=int,
                    default=DEFAULT_SHARD_BYTES)
    ap.add_argument("--batch-lines", type=int,
                    default=DEFAULT_JOB_BATCH_LINES)
    ap.add_argument("--workers", type=int, default=None,
                    help="feeder workers per host (default: auto)")
    ap.add_argument("--transport", choices=("ring", "pickle", "inline"),
                    default=None)
    ap.add_argument("--data-parallel", type=int, default=None,
                    help="chips per host for the device mesh")
    ap.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent compile-cache directory "
                         "(docs/COMPILE.md), passed through to every "
                         "host job — on a shared filesystem the first "
                         "host to compile a bucket saves every other "
                         "host that compile")
    ap.add_argument("--host-retries", type=int, default=1)
    ap.add_argument("--host-timeout", type=float, default=3600.0)
    ap.add_argument("--print-host-commands", action="store_true",
                    help="print the per-host CLI lines (for a REAL "
                         "multi-host pod over a shared filesystem) and "
                         "exit")
    return ap


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    spec = PodSpec(
        sources=list(args.sources),
        log_format=args.log_format,
        fields=list(args.fields),
        out_dir=args.out_dir,
        n_hosts=args.hosts,
        shard_bytes=args.shard_bytes,
        batch_lines=args.batch_lines,
        workers=args.workers,
        transport=args.transport,
        data_parallel=args.data_parallel,
        compile_cache=args.compile_cache,
    )
    policy = PodPolicy(host_retries=args.host_retries,
                       host_timeout_s=args.host_timeout)
    if args.print_host_commands:
        # shlex-quoted: LogFormat strings carry spaces, quotes and `%>s`
        # (a shell redirection if pasted unquoted).
        for i in range(spec.n_hosts):
            print(shlex.join(host_argv(spec, i, policy)))
        merge_argv = [sys.executable, "-m", "logparser_tpu.jobs",
                      *args.sources, "--format", args.log_format,
                      "--out", args.out_dir, "--merge-only"]
        for f in args.fields:
            merge_argv += ["--field", f]
        print("# then, once every host reports complete:")
        print(shlex.join(merge_argv))
        return 0
    try:
        report = run_pod(spec, policy=policy)
    except (ManifestError, ValueError) as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 2
    print(json.dumps(report.as_dict()))
    return 0 if report.complete else 1


if __name__ == "__main__":
    sys.exit(main())
