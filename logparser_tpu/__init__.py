"""logparser_tpu — a TPU-native access-log dissection framework.

A from-scratch rebuild of the capabilities of nielsbasjes/logparser
(/root/reference): the LogFormat configuration string is the schema; a
demand-driven dissector graph produces exactly the typed fields the user asks
for.  Unlike the reference (one compiled regex per line + reflection setters),
each LogFormat here compiles to a static field-extraction program executed over
``[batch, line_len]`` uint8 buffers on TPU, with vectorized post-stages and
columnar outputs; an exact host ("oracle") execution path provides per-line
parsing and the bit-exactness baseline.
"""

__version__ = "0.1.0"

from .observability import (  # noqa: F401
    CappedLogger,
    CounterRegistry,
    Histogram,
    MetricsRegistry,
    Tracer,
    counters,
    disable_stage_annotations,
    disable_tracing,
    enable_stage_annotations,
    enable_tracing,
    log_warning_once,
    metrics,
    observe_stage,
    pipeline_stage,
    suppressed_warning_counts,
    tracer,
    version_banner,
)
from .core import (  # noqa: F401
    Cast,
    DissectionFailure,
    Dissector,
    InvalidDissectorException,
    MissingDissectorsException,
    Parser,
    SetterPolicy,
    SimpleDissector,
    Value,
    field,
)
