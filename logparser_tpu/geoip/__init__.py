"""GeoIP subsystem: own MaxMind-DB reader, dissectors, device join tables.

Reference: httpdlog-parser/.../dissectors/geoip/ (via com.maxmind.geoip2);
rebuilt here with a pure-Python .mmdb reader (mmdb.py) and a TPU-native
flattened-range join (device.py).
"""
from .dissectors import (
    AbstractGeoIPDissector,
    GeoIPASNDissector,
    GeoIPCityDissector,
    GeoIPCountryDissector,
    GeoIPISPDissector,
)
from .device import GeoDeviceTable, ipv4_to_u32
from .mmdb import InvalidDatabaseError, MMDBReader

__all__ = [
    "AbstractGeoIPDissector",
    "GeoIPASNDissector",
    "GeoIPCityDissector",
    "GeoIPCountryDissector",
    "GeoIPISPDissector",
    "GeoDeviceTable",
    "InvalidDatabaseError",
    "MMDBReader",
    "ipv4_to_u32",
]
