"""Pure-Python MaxMind-DB (.mmdb) reader.

The reference uses com.maxmind.geoip2 ``DatabaseReader`` in MEMORY mode with a
CHM cache (AbstractGeoIPDissector.java:73-84).  No maxmind library is shipped
here, so this module implements the public MaxMind DB file format spec v2.0
directly: a binary search tree over IP bits, a type-tagged data section, and a
metadata map marked by ``\\xab\\xcd\\xefMaxMind.com`` at the end of the file.

Beyond per-IP lookup (the host/oracle path) the reader can *flatten* the tree
into sorted range tables (:meth:`MMDBReader.ipv4_ranges`) — the device-side
representation used by :mod:`logparser_tpu.geoip.device` to run IP->geo joins
as a vectorized ``searchsorted`` on TPU instead of a per-row trie walk.
"""
from __future__ import annotations

import ipaddress
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

_METADATA_MARKER = b"\xab\xcd\xefMaxMind.com"

# Data-section type tags (MaxMind DB spec).
_T_EXTENDED = 0
_T_POINTER = 1
_T_UTF8 = 2
_T_DOUBLE = 3
_T_BYTES = 4
_T_UINT16 = 5
_T_UINT32 = 6
_T_MAP = 7
_T_INT32 = 8
_T_UINT64 = 9
_T_UINT128 = 10
_T_ARRAY = 11
_T_CONTAINER = 12
_T_END_MARKER = 13
_T_BOOL = 14
_T_FLOAT = 15


class InvalidDatabaseError(ValueError):
    pass


class _Decoder:
    """Decoder for the type-tagged data section."""

    def __init__(self, buf: bytes, base: int):
        self.buf = buf
        self.base = base  # absolute offset of the data section
        self._cache: Dict[int, Any] = {}

    def decode(self, offset: int) -> Any:
        """Decode the value at ``offset`` (relative to the data section)."""
        value, _ = self._decode(offset)
        return value

    def _decode(self, offset: int) -> Tuple[Any, int]:
        buf = self.buf
        pos = self.base + offset
        ctrl = buf[pos]
        pos += 1
        type_num = ctrl >> 5

        if type_num == _T_POINTER:
            return self._decode_pointer(ctrl, pos, offset)

        if type_num == _T_EXTENDED:
            type_num = buf[pos] + 7
            pos += 1

        size = ctrl & 0x1F
        if type_num != _T_BOOL:
            if size == 29:
                size = 29 + buf[pos]
                pos += 1
            elif size == 30:
                size = 285 + int.from_bytes(buf[pos : pos + 2], "big")
                pos += 2
            elif size == 31:
                size = 65821 + int.from_bytes(buf[pos : pos + 3], "big")
                pos += 3

        if type_num == _T_UTF8:
            value: Any = buf[pos : pos + size].decode("utf-8")
            pos += size
        elif type_num == _T_BYTES:
            value = bytes(buf[pos : pos + size])
            pos += size
        elif type_num == _T_DOUBLE:
            if size != 8:
                raise InvalidDatabaseError("double must be 8 bytes")
            value = struct.unpack_from(">d", buf, pos)[0]
            pos += 8
        elif type_num == _T_FLOAT:
            if size != 4:
                raise InvalidDatabaseError("float must be 4 bytes")
            value = struct.unpack_from(">f", buf, pos)[0]
            pos += 4
        elif type_num in (_T_UINT16, _T_UINT32, _T_UINT64, _T_UINT128, _T_INT32):
            value = int.from_bytes(buf[pos : pos + size], "big", signed=False)
            if type_num == _T_INT32 and size == 4 and value >= 1 << 31:
                value -= 1 << 32
            pos += size
        elif type_num == _T_BOOL:
            value = bool(size)
        elif type_num == _T_MAP:
            value = {}
            rel = pos - self.base
            for _ in range(size):
                key, rel = self._decode(rel)
                val, rel = self._decode(rel)
                value[key] = val
            pos = self.base + rel
        elif type_num == _T_ARRAY:
            value = []
            rel = pos - self.base
            for _ in range(size):
                item, rel = self._decode(rel)
                value.append(item)
            pos = self.base + rel
        elif type_num == _T_END_MARKER:
            value = None
        else:
            raise InvalidDatabaseError(f"unexpected type number {type_num}")

        return value, pos - self.base

    def _decode_pointer(
        self, ctrl: int, pos: int, offset: int
    ) -> Tuple[Any, int]:
        buf = self.buf
        pointer_size = (ctrl >> 3) & 0x3
        value_bits = ctrl & 0x7
        if pointer_size == 0:
            target = (value_bits << 8) | buf[pos]
            pos += 1
        elif pointer_size == 1:
            target = (value_bits << 16) | int.from_bytes(buf[pos : pos + 2], "big")
            target += 2048
            pos += 2
        elif pointer_size == 2:
            target = (value_bits << 24) | int.from_bytes(buf[pos : pos + 3], "big")
            target += 526336
            pos += 3
        else:
            target = int.from_bytes(buf[pos : pos + 4], "big")
            pos += 4
        if target in self._cache:
            value = self._cache[target]
        else:
            value, _ = self._decode(target)
            self._cache[target] = value
        return value, pos - self.base


class MMDBReader:
    """Memory-mode reader for one .mmdb file (lookup + tree flattening)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.buf = f.read()
        marker_at = self.buf.rfind(_METADATA_MARKER)
        if marker_at < 0:
            raise InvalidDatabaseError(f"{path}: no MaxMind metadata marker")
        meta_decoder = _Decoder(self.buf, marker_at + len(_METADATA_MARKER))
        self.metadata: Dict[str, Any] = meta_decoder.decode(0)

        self.node_count: int = self.metadata["node_count"]
        self.record_size: int = self.metadata["record_size"]
        if self.record_size not in (24, 28, 32):
            raise InvalidDatabaseError(f"unsupported record size {self.record_size}")
        self.ip_version: int = self.metadata["ip_version"]
        self.node_bytes = self.record_size // 4  # 2 records per node
        self.tree_size = self.node_count * self.node_bytes
        # Data section starts after the tree plus a 16-byte zero separator.
        self._decoder = _Decoder(self.buf, self.tree_size + 16)
        self._ipv4_start: Optional[int] = None
        self._addr_cache: Dict[bytes, Optional[Dict[str, Any]]] = {}
        self._record_cache: Dict[int, Any] = {}

    @property
    def database_type(self) -> str:
        return self.metadata.get("database_type", "")

    # -- tree walking -------------------------------------------------------

    def _read_record(self, node: int, index: int) -> int:
        base = node * self.node_bytes
        buf = self.buf
        if self.record_size == 24:
            off = base + index * 3
            return int.from_bytes(buf[off : off + 3], "big")
        if self.record_size == 28:
            if index == 0:
                return ((buf[base + 3] & 0xF0) << 20) | int.from_bytes(
                    buf[base : base + 3], "big"
                )
            return ((buf[base + 3] & 0x0F) << 24) | int.from_bytes(
                buf[base + 4 : base + 7], "big"
            )
        off = base + index * 4
        return int.from_bytes(buf[off : off + 4], "big")

    def _ipv4_start_node(self) -> int:
        """Node reached after 96 zero bits (where IPv4 lives in a v6 tree)."""
        if self._ipv4_start is None:
            node = 0
            for _ in range(96):
                if node >= self.node_count:
                    break
                node = self._read_record(node, 0)
            self._ipv4_start = node
        return self._ipv4_start

    def lookup(self, ip: str) -> Optional[Dict[str, Any]]:
        """Look up one IP (string form); None when not found / bad input."""
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return None
        return self.lookup_address(addr)

    # Bound for the per-address result cache: real corpora repeat client
    # IPs heavily, so this converts the per-line tree walk + record decode
    # into one dict probe (the reference wraps its reader in a CHMCache
    # the same way, AbstractGeoIPDissector.java:73-84).  Crude clear-when-
    # full keeps the bound simple; refilling is one walk per address.
    _ADDR_CACHE_MAX = 65536

    def lookup_address(self, addr) -> Optional[Dict[str, Any]]:
        if addr.version == 6 and self.ip_version == 4:
            return None
        packed = addr.packed
        cache = self._addr_cache
        if packed in cache:
            return cache[packed]
        if addr.version == 4 and self.ip_version == 6:
            node = self._ipv4_start_node()
        else:
            node = 0
        bit_count = len(packed) * 8
        result: Optional[Dict[str, Any]] = None
        for i in range(bit_count):
            if node >= self.node_count:
                break
            bit = (packed[i >> 3] >> (7 - (i & 7))) & 1
            node = self._read_record(node, bit)
        if node > self.node_count:
            result = self._data_at(node)
        # node == node_count: no data; node < node_count: ran out of bits
        # inside the tree (shouldn't happen) — both cache as a miss.
        if len(cache) >= self._ADDR_CACHE_MAX:
            cache.clear()
        cache[packed] = result
        return result

    def _data_at(self, record: int) -> Any:
        # record - node_count - 16 is the offset inside the data section.
        # Distinct data records are few (shared by many ranges) — cache
        # decodes by offset, like the pointer cache inside the decoder.
        offset = record - self.node_count - 16
        if offset < 0:
            raise InvalidDatabaseError("record points into the separator")
        if offset in self._record_cache:
            return self._record_cache[offset]
        value = self._decoder.decode(offset)
        self._record_cache[offset] = value
        return value

    # -- flattening (device-side LPM tables) --------------------------------

    def networks(self) -> Iterator[Tuple[int, int, Any]]:
        """Yield ``(network_int, prefix_len, data)`` over the whole tree.

        ``network_int``/``prefix_len`` are in the tree's native bit width
        (128 for ip_version 6, 32 for 4).
        """
        total_bits = 128 if self.ip_version == 6 else 32
        stack: List[Tuple[int, int, int]] = [(0, 0, 0)]  # node, prefix, depth
        while stack:
            node, prefix, depth = stack.pop()
            if node == self.node_count:
                continue
            if node > self.node_count:
                yield prefix << (total_bits - depth) if depth else prefix, depth, (
                    self._data_at(node)
                )
                continue
            if depth >= total_bits:
                continue
            stack.append((self._read_record(node, 1), (prefix << 1) | 1, depth + 1))
            stack.append((self._read_record(node, 0), prefix << 1, depth + 1))

    def ipv4_ranges(self) -> List[Tuple[int, int, Any]]:
        """Flatten to sorted, disjoint IPv4 ``(start, end_inclusive, data)``.

        This is the LPM-free representation for the TPU join path: a sorted
        ``starts`` array + parallel ``ends``/row arrays, looked up per IP with
        ``searchsorted`` (logparser_tpu.geoip.device).
        """
        v4_mapped_prefix = 0  # v4 sits at ::/96 in a v6 tree
        out: List[Tuple[int, int, Any]] = []
        if self.ip_version == 4:
            for net, plen, data in self.networks():
                size = 1 << (32 - plen)
                out.append((net, net + size - 1, data))
        else:
            for net, plen, data in self.networks():
                if plen < 96:
                    # A shorter-than-96 prefix covering ::/96 also covers all
                    # of IPv4; clip to the v4 space if it contains it.
                    span = 1 << (128 - plen)
                    if net <= v4_mapped_prefix < net + span:
                        out.append((0, 0xFFFFFFFF, data))
                    continue
                if (net >> 32) != 0:
                    continue  # not inside ::/96
                size = 1 << (128 - plen)
                start = net & 0xFFFFFFFF
                out.append((start, start + size - 1, data))
        out.sort(key=lambda t: t[0])
        return out
