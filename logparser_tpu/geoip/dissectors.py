"""GeoIP dissectors: IP -> continent/country/city/ASN/ISP fields.

Reference behavior: httpdlog-parser/.../dissectors/geoip/*.java —
``AbstractGeoIPDissector`` (input type ``IP``, db path via ctor or
``initializeFromSettingsParameter``, reader opened in ``prepareForRun``,
AbstractGeoIPDissector.java:56-84), ``GeoIPCountryDissector``
(GeoIPCountryDissector.java:50-58), ``GeoIPCityDissector`` extends it
(GeoIPCityDissector.java:55-71, most-specific subdivision :207),
``GeoIPASNDissector`` (:50-51) and ``GeoIPISPDissector`` extends ASN (:48-49).

The lookup engine is :class:`logparser_tpu.geoip.mmdb.MMDBReader` (own
implementation of the public MaxMind-DB format; the reference links
com.maxmind.geoip2).  Locale for ``names`` maps is ``en``, matching
DatabaseReader's default.
"""
from __future__ import annotations

import ipaddress
from typing import Any, Dict, FrozenSet, List, Optional, Set

from ..core.casts import (
    Cast,
    NO_CASTS,
    STRING_ONLY,
    STRING_OR_DOUBLE,
    STRING_OR_LONG,
)
from ..core.dissector import Dissector, extract_field_name
from ..core.exceptions import InvalidDissectorException
from ..core.parsable import Parsable
from .mmdb import MMDBReader


def _name_en(node: Optional[Dict[str, Any]]) -> Optional[str]:
    if not node:
        return None
    names = node.get("names")
    if not names:
        return None
    return names.get("en")


class AbstractGeoIPDissector(Dissector):
    """Base: parses the IP, opens the reader once, delegates to subclasses."""

    INPUT_TYPE = "IP"

    def __init__(self, database_file_name: Optional[str] = None):
        self.database_file_name = database_file_name
        self._reader: Optional[MMDBReader] = None
        self._wanted: Set[str] = set()

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        self.database_file_name = settings
        return True

    def initialize_new_instance(self, new_instance: "Dissector") -> None:
        new_instance.initialize_from_settings_parameter(self.database_file_name)

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    # {relative field name -> casts}; subclasses extend this table.
    _CASTS_TABLE: Dict[str, FrozenSet[Cast]] = {}

    def prepare_for_dissect(self, input_name: str, output_name: str) -> FrozenSet[Cast]:
        name = extract_field_name(input_name, output_name)
        casts = self._CASTS_TABLE.get(name)
        if casts is None:
            return NO_CASTS
        self._wanted.add(name)
        return casts

    def prepare_for_run(self) -> None:
        try:
            self._reader = MMDBReader(self.database_file_name)
        except (OSError, ValueError, TypeError) as e:
            # Same shape as AbstractGeoIPDissector.java:80-82 so the adapters'
            # error surfaces match ("<class>:<message>") — covers missing
            # files, corrupt databases (InvalidDatabaseError) and an unset
            # database path alike.
            detail = getattr(e, "strerror", None) or e
            raise InvalidDissectorException(
                f"{type(self).__name__}:{self.database_file_name} ({detail})"
            )

    def dissect(self, parsable: Parsable, input_name: str) -> None:
        field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        if field is None:
            return
        value = field.value.get_string()
        if not value:
            return
        try:
            addr = ipaddress.ip_address(value)
        except ValueError:
            return
        data = self._reader.lookup_address(addr) if self._reader else None
        if data is None:
            return
        self.extract(parsable, input_name, data)

    def extract(self, parsable: Parsable, input_name: str, data: Dict[str, Any]) -> None:
        raise NotImplementedError

    def _want(self, name: str) -> bool:
        return name in self._wanted


class GeoIPCountryDissector(AbstractGeoIPDissector):
    """continent.name/.code + country.name/.iso/.getconfidence/.isineuropeanunion
    (GeoIPCountryDissector.java:50-58, 126-155)."""

    _CASTS_TABLE = {
        "continent.name": STRING_ONLY,
        "continent.code": STRING_ONLY,
        "country.name": STRING_ONLY,
        "country.iso": STRING_ONLY,
        "country.getconfidence": STRING_OR_LONG,
        "country.isineuropeanunion": STRING_OR_LONG,
    }

    def get_possible_output(self) -> List[str]:
        return [
            "STRING:continent.name",
            "STRING:continent.code",
            "STRING:country.name",
            "STRING:country.iso",
            "NUMBER:country.getconfidence",
            "BOOLEAN:country.isineuropeanunion",
        ]

    def extract(self, parsable: Parsable, input_name: str, data: Dict[str, Any]) -> None:
        continent = data.get("continent")
        if continent:
            if self._want("continent.name"):
                parsable.add_dissection(
                    input_name, "STRING", "continent.name", _name_en(continent)
                )
            if self._want("continent.code"):
                parsable.add_dissection(
                    input_name, "STRING", "continent.code", continent.get("code")
                )
        country = data.get("country")
        if country:
            if self._want("country.name"):
                parsable.add_dissection(
                    input_name, "STRING", "country.name", _name_en(country)
                )
            if self._want("country.iso"):
                parsable.add_dissection(
                    input_name, "STRING", "country.iso", country.get("iso_code")
                )
            if self._want("country.getconfidence"):
                parsable.add_dissection(
                    input_name, "NUMBER", "country.getconfidence",
                    country.get("confidence"),
                )
            if self._want("country.isineuropeanunion"):
                parsable.add_dissection(
                    input_name, "BOOLEAN", "country.isineuropeanunion",
                    1 if country.get("is_in_european_union") else 0,
                )


class GeoIPCityDissector(GeoIPCountryDissector):
    """Adds subdivision/city/postal/location fields
    (GeoIPCityDissector.java:55-71, 200-277); subdivision is the most
    specific one, i.e. the last entry (:207)."""

    _CASTS_TABLE = {
        **GeoIPCountryDissector._CASTS_TABLE,
        "subdivision.name": STRING_ONLY,
        "subdivision.iso": STRING_ONLY,
        "city.name": STRING_ONLY,
        "city.confidence": STRING_OR_LONG,
        "city.geonameid": STRING_OR_LONG,
        "postal.code": STRING_ONLY,
        "postal.confidence": STRING_OR_LONG,
        "location.latitude": STRING_OR_DOUBLE,
        "location.longitude": STRING_OR_DOUBLE,
        "location.timezone": STRING_ONLY,
        "location.accuracyradius": STRING_OR_LONG,
        "location.averageincome": STRING_OR_LONG,
        "location.metrocode": STRING_OR_LONG,
        "location.populationdensity": STRING_OR_LONG,
    }

    def get_possible_output(self) -> List[str]:
        return super().get_possible_output() + [
            "STRING:subdivision.name",
            "STRING:subdivision.iso",
            "STRING:city.name",
            "NUMBER:city.confidence",
            "NUMBER:city.geonameid",
            "STRING:postal.code",
            "NUMBER:postal.confidence",
            "STRING:location.latitude",
            "STRING:location.longitude",
            "STRING:location.timezone",
            "NUMBER:location.accuracyradius",
            "NUMBER:location.averageincome",
            "NUMBER:location.metrocode",
            "NUMBER:location.populationdensity",
        ]

    def extract(self, parsable: Parsable, input_name: str, data: Dict[str, Any]) -> None:
        super().extract(parsable, input_name, data)

        subdivisions = data.get("subdivisions") or []
        if subdivisions:
            subdivision = subdivisions[-1]  # most specific
            if self._want("subdivision.name"):
                parsable.add_dissection(
                    input_name, "STRING", "subdivision.name", _name_en(subdivision)
                )
            if self._want("subdivision.iso"):
                parsable.add_dissection(
                    input_name, "STRING", "subdivision.iso",
                    subdivision.get("iso_code"),
                )

        city = data.get("city")
        if city:
            if self._want("city.name"):
                parsable.add_dissection(
                    input_name, "STRING", "city.name", _name_en(city)
                )
            if self._want("city.confidence"):
                parsable.add_dissection(
                    input_name, "NUMBER", "city.confidence", city.get("confidence")
                )
            if self._want("city.geonameid"):
                geoname = city.get("geoname_id")
                parsable.add_dissection(
                    input_name, "NUMBER", "city.geonameid",
                    int(geoname) if geoname is not None else None,
                )

        postal = data.get("postal")
        if postal:
            if self._want("postal.code"):
                parsable.add_dissection(
                    input_name, "STRING", "postal.code", postal.get("code")
                )
            if self._want("postal.confidence"):
                parsable.add_dissection(
                    input_name, "NUMBER", "postal.confidence",
                    postal.get("confidence"),
                )

        location = data.get("location")
        if location:
            if self._want("location.latitude"):
                parsable.add_dissection(
                    input_name, "STRING", "location.latitude",
                    _as_float(location.get("latitude")),
                )
            if self._want("location.longitude"):
                parsable.add_dissection(
                    input_name, "STRING", "location.longitude",
                    _as_float(location.get("longitude")),
                )
            if self._want("location.timezone"):
                parsable.add_dissection(
                    input_name, "STRING", "location.timezone",
                    location.get("time_zone"),
                )
            if self._want("location.accuracyradius"):
                parsable.add_dissection(
                    input_name, "NUMBER", "location.accuracyradius",
                    location.get("accuracy_radius"),
                )
            # The reference only emits these when non-null
            # (GeoIPCityDissector.java:261-276).
            if self._want("location.averageincome"):
                value = location.get("average_income")
                if value is not None:
                    parsable.add_dissection(
                        input_name, "NUMBER", "location.averageincome", value
                    )
            if self._want("location.metrocode"):
                value = location.get("metro_code")
                if value is not None:
                    parsable.add_dissection(
                        input_name, "NUMBER", "location.metrocode", value
                    )
            if self._want("location.populationdensity"):
                value = location.get("population_density")
                if value is not None:
                    parsable.add_dissection(
                        input_name, "NUMBER", "location.populationdensity", value
                    )


def _as_float(v: Any) -> Optional[float]:
    return None if v is None else float(v)


class GeoIPASNDissector(AbstractGeoIPDissector):
    """asn.number + asn.organization (GeoIPASNDissector.java:50-51, 88-96)."""

    _CASTS_TABLE = {
        "asn.number": STRING_OR_LONG,
        "asn.organization": STRING_ONLY,
    }

    def get_possible_output(self) -> List[str]:
        return ["ASN:asn.number", "STRING:asn.organization"]

    def extract(self, parsable: Parsable, input_name: str, data: Dict[str, Any]) -> None:
        number = data.get("autonomous_system_number")
        if number is not None and self._want("asn.number"):
            parsable.add_dissection(input_name, "ASN", "asn.number", number)
        org = data.get("autonomous_system_organization")
        if org is not None and self._want("asn.organization"):
            parsable.add_dissection(input_name, "STRING", "asn.organization", org)


class GeoIPISPDissector(GeoIPASNDissector):
    """Adds isp.name + isp.organization (GeoIPISPDissector.java:48-49, 91-99)."""

    _CASTS_TABLE = {
        **GeoIPASNDissector._CASTS_TABLE,
        "isp.name": STRING_ONLY,
        "isp.organization": STRING_ONLY,
    }

    def get_possible_output(self) -> List[str]:
        return super().get_possible_output() + [
            "STRING:isp.name",
            "STRING:isp.organization",
        ]

    def extract(self, parsable: Parsable, input_name: str, data: Dict[str, Any]) -> None:
        super().extract(parsable, input_name, data)
        isp = data.get("isp")
        if isp is not None and self._want("isp.name"):
            parsable.add_dissection(input_name, "STRING", "isp.name", isp)
        org = data.get("organization")
        if org is not None and self._want("isp.organization"):
            parsable.add_dissection(input_name, "STRING", "isp.organization", org)
