"""Device-side IP->geo join: flattened LPM tables + vectorized searchsorted.

The reference walks the MaxMind binary trie per record on the host
(AbstractGeoIPDissector.java:73-84 keeps the trie in memory and caches nodes).
A per-row trie walk is hostile to TPU execution, so this module flattens the
tree once on host (MMDBReader.ipv4_ranges) into three parallel arrays:

    starts[K]  uint32, sorted   range lower bounds
    ends[K]    uint32           inclusive upper bounds
    rows[K]    int32            row index into extracted columns (-1 = none)

and looks up a whole batch of IPs with ONE ``jnp.searchsorted`` + gather —
an O(log K) SIMD join that XLA fuses with the surrounding stages.  String
columns become vocabulary indices (host keeps the vocab); numeric columns are
materialized as device arrays.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .mmdb import MMDBReader

# Column extractors: path name -> fn(record dict) -> python value or None.
_EXTRACTORS: Dict[str, Callable[[Dict[str, Any]], Any]] = {
    "continent.code": lambda d: (d.get("continent") or {}).get("code"),
    "continent.name": lambda d: ((d.get("continent") or {}).get("names") or {}).get("en"),
    "country.iso": lambda d: (d.get("country") or {}).get("iso_code"),
    "country.name": lambda d: ((d.get("country") or {}).get("names") or {}).get("en"),
    "city.name": lambda d: ((d.get("city") or {}).get("names") or {}).get("en"),
    "postal.code": lambda d: (d.get("postal") or {}).get("code"),
    "location.latitude": lambda d: (d.get("location") or {}).get("latitude"),
    "location.longitude": lambda d: (d.get("location") or {}).get("longitude"),
    "location.timezone": lambda d: (d.get("location") or {}).get("time_zone"),
    "asn.number": lambda d: d.get("autonomous_system_number"),
    "asn.organization": lambda d: d.get("autonomous_system_organization"),
    "isp.name": lambda d: d.get("isp"),
    "isp.organization": lambda d: d.get("organization"),
}

_FLOAT_COLUMNS = {"location.latitude", "location.longitude"}
_INT_COLUMNS = {"asn.number"}


class GeoDeviceTable:
    """Flattened .mmdb as device arrays + host vocabularies."""

    def __init__(self, reader: MMDBReader, columns: Sequence[str]):
        unknown = [c for c in columns if c not in _EXTRACTORS]
        if unknown:
            raise ValueError(f"unsupported geo columns: {unknown}")
        self.columns = list(columns)

        ranges = reader.ipv4_ranges()
        starts: List[int] = []
        ends: List[int] = []
        per_col: Dict[str, List[Any]] = {c: [] for c in columns}
        for start, end, data in ranges:
            starts.append(start)
            ends.append(end)
            for c in columns:
                per_col[c].append(_EXTRACTORS[c](data))

        self.starts = np.asarray(starts, dtype=np.uint32)
        self.ends = np.asarray(ends, dtype=np.uint32)

        # Row 0 of every column array is the "miss" row.
        self.vocabs: Dict[str, List[Optional[str]]] = {}
        self.arrays: Dict[str, np.ndarray] = {}
        for c in columns:
            values = per_col[c]
            if c in _FLOAT_COLUMNS:
                self.arrays[c] = np.asarray(
                    [np.nan] + [np.nan if v is None else float(v) for v in values],
                    dtype=np.float32,
                )
            elif c in _INT_COLUMNS:
                self.arrays[c] = np.asarray(
                    [-1] + [-1 if v is None else int(v) for v in values],
                    dtype=np.int64,
                )
            else:
                vocab: List[Optional[str]] = [None]
                index: Dict[Optional[str], int] = {None: 0}
                idx_col = []
                for v in values:
                    if v not in index:
                        index[v] = len(vocab)
                        vocab.append(v)
                    idx_col.append(index[v])
                self.vocabs[c] = vocab
                self.arrays[c] = np.asarray([0] + idx_col, dtype=np.int32)

        # Object-array views of the vocabularies, built once: the batch
        # materializer indexes these per batch (a production City database
        # has ~1e5 names; rebuilding per batch would be O(vocab) each time).
        self.vocab_arrays: Dict[str, np.ndarray] = {
            c: np.asarray(v, dtype=object) for c, v in self.vocabs.items()
        }

    def lookup_rows(self, ips_u32):
        """[B] uint32 -> [B] int32 row (0 = miss; row r = range r-1). Jittable."""
        import jax.numpy as jnp

        starts = jnp.asarray(self.starts)
        ends = jnp.asarray(self.ends)
        ips = jnp.asarray(ips_u32, dtype=jnp.uint32)
        pos = jnp.searchsorted(starts, ips, side="right")  # 1-based candidate
        idx = jnp.clip(pos - 1, 0, max(len(self.starts) - 1, 0))
        hit = (pos > 0) & (ips <= ends[idx]) & (ips >= starts[idx])
        return jnp.where(hit, pos.astype(jnp.int32), 0)

    def gather(self, column: str, rows):
        """Gather one column for looked-up rows. Jittable."""
        import jax.numpy as jnp

        return jnp.asarray(self.arrays[column])[rows]

    def decode_strings(self, column: str, rows: np.ndarray) -> List[Optional[str]]:
        """Host-side: vocab indices -> strings (None = miss)."""
        vocab = self.vocabs[column]
        arr = self.arrays[column]
        return [vocab[int(arr[int(r)])] for r in rows]


def ipv4_to_u32(ips: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Host helper: dotted-quad strings -> (uint32 array, ok mask)."""
    out = np.zeros(len(ips), dtype=np.uint32)
    ok = np.zeros(len(ips), dtype=bool)
    for i, s in enumerate(ips):
        parts = s.split(".") if isinstance(s, str) else []
        if len(parts) == 4:
            try:
                vals = [int(p) for p in parts]
            except ValueError:
                continue
            if all(0 <= v <= 255 for v in vals):
                out[i] = (
                    (vals[0] << 24) | (vals[1] << 16) | (vals[2] << 8) | vals[3]
                )
                ok[i] = True
    return out, ok
