"""Avro-record streaming variants (Beam DoFn / Flink MapFunction shape).

Reference behavior: examples/apache-beam/.../avro/TestParserDoFnAvro.java and
examples/apache-flink/.../avro/TestParserMapFunctionAvroClass.java — the
record handed to the pipeline is not a flat map but a NESTED Avro ``Click``
record (Device / Browser / Visitor{ISP, GeoLocation}), filled through
``@Field`` setters that route each parsed value into the right sub-builder,
with ScreenResolution + GeoIP dissectors chained onto the parser.

This is the same shape, tpu-native:

* the schema is the Python rendering of the reference's BeamTestRecord.avdl
  (examples/apache-beam/src/test/avro/TestRecord.avdl);
* ``ClickSetter`` uses the framework's ``@field`` decorator (core/fields.py)
  to build the nested record — setter-per-path, exactly the reference's
  ``Builder<Click>`` pattern;
* records round-trip through real Avro BINARY encoding.  The image has no
  avro library, so ``_avro_codec`` implements the (tiny) relevant subset of
  the Avro spec — zigzag-varint longs, utf8 strings with length prefix,
  little-endian doubles, records as field concatenation — enough to encode
  and decode any schema this example declares.  If ``fastavro`` or ``avro``
  is installed the same bytes are valid input for them.
"""
import io
import struct
from typing import Any, Dict, List

from logparser_tpu.core.fields import field
from logparser_tpu.dissectors.screenres import ScreenResolutionDissector
from logparser_tpu.geoip import GeoIPCityDissector, GeoIPISPDissector
from logparser_tpu.httpd import HttpdLoglineParser

# ---------------------------------------------------------------------------
# Schema: the reference's BeamTestRecord.avdl rendered as Avro JSON schema.

CLICK_SCHEMA: Dict[str, Any] = {
    "type": "record",
    "name": "Click",
    "namespace": "logparser_tpu.record",
    "fields": [
        {"name": "timestamp", "type": "long"},
        {"name": "device", "type": {
            "type": "record", "name": "Device", "fields": [
                {"name": "screenWidth", "type": "long"},
                {"name": "screenHeight", "type": "long"},
            ]}},
        {"name": "browser", "type": {
            "type": "record", "name": "Browser", "fields": [
                {"name": "useragent", "type": "string"},
            ]}},
        {"name": "visitor", "type": {
            "type": "record", "name": "Visitor", "fields": [
                {"name": "ip", "type": "string"},
                {"name": "isp", "type": {
                    "type": "record", "name": "ISP", "fields": [
                        {"name": "asnNumber", "type": "string"},
                        {"name": "asnOrganization", "type": "string"},
                        {"name": "ispName", "type": "string"},
                        {"name": "ispOrganization", "type": "string"},
                    ]}},
                {"name": "geoLocation", "type": {
                    "type": "record", "name": "GeoLocation", "fields": [
                        {"name": "continentName", "type": "string"},
                        {"name": "continentCode", "type": "string"},
                        {"name": "countryName", "type": "string"},
                        {"name": "countryIso", "type": "string"},
                        {"name": "subdivisionName", "type": "string"},
                        {"name": "subdivisionIso", "type": "string"},
                        {"name": "cityName", "type": "string"},
                        {"name": "postalCode", "type": "string"},
                        {"name": "locationLatitude", "type": "double"},
                        {"name": "locationLongitude", "type": "double"},
                    ]}},
            ]}},
    ],
}


class _avro_codec:
    """Minimal Avro binary codec for string/long/double/record schemas."""

    @staticmethod
    def _zigzag(n: int) -> int:
        return (n << 1) ^ (n >> 63)

    @staticmethod
    def _unzigzag(n: int) -> int:
        return (n >> 1) ^ -(n & 1)

    @classmethod
    def _write_long(cls, out: io.BytesIO, n: int) -> None:
        n = cls._zigzag(int(n))
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                out.write(bytes([b | 0x80]))
            else:
                out.write(bytes([b]))
                return

    @classmethod
    def _read_long(cls, buf: io.BytesIO) -> int:
        shift, acc = 0, 0
        while True:
            (b,) = buf.read(1)
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                return cls._unzigzag(acc)
            shift += 7

    @classmethod
    def encode(cls, schema: Any, value: Any, out: io.BytesIO) -> None:
        t = schema["type"] if isinstance(schema, dict) else schema
        if t == "record":
            for f in schema["fields"]:
                cls.encode(f["type"], value[f["name"]], out)
        elif t == "long":
            cls._write_long(out, value)
        elif t == "double":
            out.write(struct.pack("<d", float(value)))
        elif t == "string":
            raw = str(value).encode("utf-8")
            cls._write_long(out, len(raw))
            out.write(raw)
        else:
            raise NotImplementedError(f"schema type {t!r}")

    @classmethod
    def decode(cls, schema: Any, buf: io.BytesIO) -> Any:
        t = schema["type"] if isinstance(schema, dict) else schema
        if t == "record":
            return {
                f["name"]: cls.decode(f["type"], buf) for f in schema["fields"]
            }
        if t == "long":
            return cls._read_long(buf)
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "string":
            return buf.read(cls._read_long(buf)).decode("utf-8")
        raise NotImplementedError(f"schema type {t!r}")


def encode_click(click: Dict[str, Any]) -> bytes:
    out = io.BytesIO()
    _avro_codec.encode(CLICK_SCHEMA, click, out)
    return out.getvalue()


def decode_click(raw: bytes) -> Dict[str, Any]:
    return _avro_codec.decode(CLICK_SCHEMA, io.BytesIO(raw))


# ---------------------------------------------------------------------------
# The setter record: @field-per-path into nested builders
# (reference: TestParserDoFnAvro.ClickSetter).


class ClickSetter:
    def __init__(self):
        self.click: Dict[str, Any] = {
            "timestamp": 0,
            "device": {"screenWidth": 0, "screenHeight": 0},
            "browser": {"useragent": ""},
            "visitor": {
                "ip": "",
                "isp": {"asnNumber": "", "asnOrganization": "",
                        "ispName": "", "ispOrganization": ""},
                "geoLocation": {
                    "continentName": "", "continentCode": "",
                    "countryName": "", "countryIso": "",
                    "subdivisionName": "", "subdivisionIso": "",
                    "cityName": "", "postalCode": "",
                    "locationLatitude": 0.0, "locationLongitude": 0.0,
                },
            },
        }

    @field("TIME.EPOCH:request.receive.time.epoch")
    def set_timestamp(self, value: int):
        self.click["timestamp"] = value

    @field("SCREENWIDTH:request.firstline.uri.query.s.width")
    def set_screen_width(self, value: int):
        self.click["device"]["screenWidth"] = value

    @field("SCREENHEIGHT:request.firstline.uri.query.s.height")
    def set_screen_height(self, value: int):
        self.click["device"]["screenHeight"] = value

    @field("HTTP.USERAGENT:request.user-agent")
    def set_useragent(self, value: str):
        self.click["browser"]["useragent"] = value

    @field("IP:connection.client.host")
    def set_ip(self, value: str):
        self.click["visitor"]["ip"] = value

    @field("ASN:connection.client.host.asn.number")
    def set_asn_number(self, value: str):
        self.click["visitor"]["isp"]["asnNumber"] = str(value)

    @field("STRING:connection.client.host.asn.organization")
    def set_asn_organization(self, value: str):
        self.click["visitor"]["isp"]["asnOrganization"] = value

    @field("STRING:connection.client.host.isp.name")
    def set_isp_name(self, value: str):
        self.click["visitor"]["isp"]["ispName"] = value

    @field("STRING:connection.client.host.isp.organization")
    def set_isp_organization(self, value: str):
        self.click["visitor"]["isp"]["ispOrganization"] = value

    @field("STRING:connection.client.host.continent.name")
    def set_continent_name(self, value: str):
        self.click["visitor"]["geoLocation"]["continentName"] = value

    @field("STRING:connection.client.host.continent.code")
    def set_continent_code(self, value: str):
        self.click["visitor"]["geoLocation"]["continentCode"] = value

    @field("STRING:connection.client.host.country.name")
    def set_country_name(self, value: str):
        self.click["visitor"]["geoLocation"]["countryName"] = value

    @field("STRING:connection.client.host.country.iso")
    def set_country_iso(self, value: str):
        self.click["visitor"]["geoLocation"]["countryIso"] = value

    @field("STRING:connection.client.host.subdivision.name")
    def set_subdivision_name(self, value: str):
        self.click["visitor"]["geoLocation"]["subdivisionName"] = value

    @field("STRING:connection.client.host.subdivision.iso")
    def set_subdivision_iso(self, value: str):
        self.click["visitor"]["geoLocation"]["subdivisionIso"] = value

    @field("STRING:connection.client.host.city.name")
    def set_city_name(self, value: str):
        self.click["visitor"]["geoLocation"]["cityName"] = value

    @field("STRING:connection.client.host.postal.code")
    def set_postal_code(self, value: str):
        self.click["visitor"]["geoLocation"]["postalCode"] = value

    @field("STRING:connection.client.host.location.latitude")
    def set_latitude(self, value: float):
        self.click["visitor"]["geoLocation"]["locationLatitude"] = float(value)

    @field("STRING:connection.client.host.location.longitude")
    def set_longitude(self, value: float):
        self.click["visitor"]["geoLocation"]["locationLongitude"] = float(value)


def build_parser(city_mmdb: str, isp_mmdb: str) -> HttpdLoglineParser:
    p = HttpdLoglineParser(ClickSetter, "combined")
    p.add_dissector(ScreenResolutionDissector())
    p.add_type_remapping(
        "request.firstline.uri.query.s", "SCREENRESOLUTION"
    )
    p.add_dissector(GeoIPISPDissector(isp_mmdb))
    p.add_dissector(GeoIPCityDissector(city_mmdb))
    return p


class AvroParserDoFn:
    """Beam DoFn shape: one Avro-encoded Click per log line."""

    def __init__(self, city_mmdb: str, isp_mmdb: str):
        self._paths = (city_mmdb, isp_mmdb)

    def setup(self):
        self._parser = build_parser(*self._paths)

    def process_element(self, line: str) -> List[bytes]:
        setter = self._parser.parse(line, ClickSetter())
        return [encode_click(setter.click)]


class AvroParserMapFunction:
    """Flink RichMapFunction shape over the same parser/record."""

    def __init__(self, city_mmdb: str, isp_mmdb: str):
        self._paths = (city_mmdb, isp_mmdb)

    def open(self):
        self._parser = build_parser(*self._paths)

    def map(self, line: str) -> bytes:
        setter = self._parser.parse(line, ClickSetter())
        return encode_click(setter.click)


INPUT_LINE = (
    '80.100.47.45 - - [25/Dec/2021:10:24:05 +0100] '
    '"GET /index.html?s=1280x1024 HTTP/1.1" 200 123 '
    '"http://example.com/from" "Mozilla/5.0 (Demo)"'
)


def main() -> Dict[str, Any]:
    from logparser_tpu.tools.geoip_testdata import ensure_test_databases
    import os

    data = ensure_test_databases()
    city = os.path.join(data, "GeoIP2-City-Test.mmdb")
    isp = os.path.join(data, "GeoIP2-ISP-Test.mmdb")

    fn = AvroParserDoFn(city, isp)
    fn.setup()
    (raw,) = fn.process_element(INPUT_LINE)

    flink = AvroParserMapFunction(city, isp)
    flink.open()
    raw2 = flink.map(INPUT_LINE)
    assert raw2 == raw, "DoFn and MapFunction must build identical records"

    click = decode_click(raw)
    print(f"Avro Click record ({len(raw)} bytes binary):")
    print(click)
    return click


if __name__ == "__main__":
    main()
