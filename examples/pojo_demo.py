"""Canonical API demo: discovery + annotated-record parsing.

Reference behavior: examples/java-pojo/.../Main.java:34-90 — first list every
possible output path (with casts) for a hairy custom LogFormat using a dummy
parser, then parse one real-world logline into a record class whose setters
are marked with field annotations.
"""
from logparser_tpu.core import Parser, field
from logparser_tpu.httpd import HttpdLoglineParser

# A deliberately gnarly LogFormat: custom field order, %D response time,
# request headers, an environment variable, cookies, and a quoted host header.
LOG_FORMAT = (
    "%t %u [%D %h %{True-Client-IP}i %{UNIQUE_ID}e %r] %{Cookie}i %s "
    '"%{User-Agent}i" "%{host}i" %l %b %{Referer}i'
)

LOG_LINE = (
    "[02/Dec/2013:14:10:30 -0000] - [52075 10.102.4.254 177.43.52.210 "
    "UpyU1gpmBAwAACfd5W0AAAAW GET /products/NY-019.jpg.rendition.zoomable.jpg "
    "HTTP/1.1] firstvisit=http%3A%2F%2Fwww.example.com%2Fen-us||1372268254000; "
    "has_js=1; session=julinho%3A5248423a; lang=en 200 "
    '"Mozilla/5.0 (Windows NT 6.2; WOW64) AppleWebKit/537.36 (KHTML, like '
    'Gecko) Chrome/31.0.1650.57 Safari/537.36" "www.example.com" - 463952 '
    "http://www.example.com/content/report/shows/New_York/trip/sheers.html"
)


class MyRecord:
    """The POJO equivalent: setters marked with @field get the values."""

    def __init__(self):
        self.results = {}

    @field("IP:connection.client.host")
    def set_ip(self, value: str):
        self.results["ip"] = value

    @field("TIME.STAMP:request.receive.time")
    def set_time(self, value: str):
        self.results["time"] = value

    @field("MICROSECONDS:response.server.processing.time")
    def set_process_time(self, value: int):
        self.results["process.time.us"] = value

    @field("HTTP.METHOD:request.firstline.method")
    def set_method(self, value: str):
        self.results["method"] = value

    @field("HTTP.PATH:request.firstline.uri.path")
    def set_path(self, value: str):
        self.results["uri.path"] = value

    @field("STRING:request.status")
    def set_status(self, value: str):
        self.results["status"] = value

    @field("BYTESCLF:response.body.bytes")
    def set_bytes(self, value: int):
        self.results["body.bytes"] = value

    @field("HTTP.COOKIE:request.cookies.*")
    def set_cookie(self, name: str, value: str):
        self.results[name] = value

    @field("HTTP.USERAGENT:request.user-agent")
    def set_useragent(self, value: str):
        self.results["useragent"] = value

    def __str__(self):
        return "\n".join(f"  {k} = {v!r}" for k, v in sorted(self.results.items()))


def print_all_possibles(log_format: str) -> None:
    # To figure out what values we CAN get from this format we instantiate
    # the parser with no record class at all (Main.java:36-38 uses a dummy
    # Object.class the same way).
    dummy_parser = HttpdLoglineParser(None, log_format)
    possible_paths = dummy_parser.get_possible_paths()

    # getCasts needs an actually-assembled parser, so register every path
    # against a throwaway setter first (Main.java:43-47).
    dummy_parser.record_class = type("Dummy", (), {"sink": lambda self, v: None})
    dummy_parser.add_parse_target("sink", possible_paths)
    dummy_parser.ignore_missing_dissectors()

    print("==================================")
    print("Possible output:")
    for path in possible_paths:
        casts = dummy_parser.get_casts(path)
        names = sorted(c.name for c in casts) if casts else None
        print(f"{path}     {names}")
    print("==================================")


def main() -> MyRecord:
    print_all_possibles(LOG_FORMAT)

    parser = HttpdLoglineParser(MyRecord, LOG_FORMAT)
    record = parser.parse(LOG_LINE)

    print("================================================================")
    print(record)
    print("================================================================")
    return record


if __name__ == "__main__":
    main()
