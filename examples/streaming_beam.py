"""Beam-style DoFn embedding with micro-batching.

Reference behavior: examples/apache-beam/.../TestParserDoFn.java — a DoFn
holding a parser built from serialized config, invoked per element.  The
framework's ``MicroBatcher`` keeps that per-element surface while actually
parsing in TPU-sized batches: ``feed()`` buffers elements and returns finished
(element, record) pairs whenever a batch fills; ``flush()`` drains the rest
(the bundle-finish hook).
"""
from typing import List

from logparser_tpu.adapters.streaming import (
    MicroBatcher,
    ParserConfig,
    ParserMapOperator,
)
from logparser_tpu.tools.demolog import generate_combined_lines

FIELDS = [
    "IP:connection.client.host",
    "HTTP.URI:request.firstline.uri",
    "BYTES:response.body.bytes",
]


class ParserDoFn:
    """process_element/finish_bundle surface over the micro-batched operator."""

    def __init__(self, config: ParserConfig):
        self._config = config

    def setup(self):
        self._operator = ParserMapOperator(self._config)
        self._operator.open()
        self._batcher = MicroBatcher(self._operator)

    def process_element(self, element):
        return self._batcher.feed(element)

    def finish_bundle(self):
        return self._batcher.flush()

    def teardown(self):
        self._operator.close()


def main() -> List:
    fn = ParserDoFn(ParserConfig(log_format="combined", fields=FIELDS))
    fn.setup()
    out = []
    try:
        for line in generate_combined_lines(300, seed=5):
            out.extend(fn.process_element(line))
        out.extend(fn.finish_bundle())
    finally:
        fn.teardown()

    parsed = [record for _, record in out if record is not None]
    print(f"DoFn produced {len(parsed)} records over {len(out)} elements; first:")
    for fid in FIELDS:
        print(f"  {fid} = {parsed[0].get(fid.split(':', 1)[1])!r}")
    return parsed


if __name__ == "__main__":
    main()
