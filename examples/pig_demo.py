"""The Pig-style string-configured Loader, end to end.

Reference behavior: examples/apache-pig/src/main/pig/{fields,example,demo}.pig
— everything is configured through the Loader's string-parameter protocol:
the logformat, requested fields, ``-map:path:TYPE`` remappings, and
``-load:classpath:param`` dynamic dissector loading.  `fields` mode lists all
possible paths; `example` mode prints a ready-to-paste script.
"""
import os
import tempfile
from typing import List, Tuple

from logparser_tpu.adapters.loader import Loader
from logparser_tpu.tools.demolog import generate_combined_lines

LOG_FORMAT = "combined"


def fields_mode() -> List[Tuple]:
    """fields.pig: list every possible field for the format."""
    loader = Loader(LOG_FORMAT, "fields")
    rows = []
    print("---- fields mode ----")
    for row in loader.load("unused-in-fields-mode"):
        print(f"  {row}")
        rows.append(row)
    return rows


def example_mode() -> str:
    """example.pig: generate a ready-made script for this format."""
    loader = Loader(
        LOG_FORMAT,
        "example",
        "-map:request.firstline.uri.query.g:HTTP.URI",
        "-load:examples.url_class_dissector.UrlClassDissector:",
    )
    script = loader.create_example()
    print("---- example mode ----")
    print(script)
    return script


def demo_query(log_path: str) -> List[Tuple]:
    """demo.pig: a real load with remapping, a dynamically loaded custom
    dissector, and wildcard map outputs."""
    loader = Loader(
        LOG_FORMAT,
        "HTTP.PATH:request.firstline.uri.path",
        "HTTP.PATH.CLASS:request.firstline.uri.path.class",
        "-load:examples.url_class_dissector.UrlClassDissector:",
        "IP:connection.client.host",
        "TIME.STAMP:request.receive.time",
        "STRING:request.firstline.uri.query.*",
        "HTTP.USERAGENT:request.user-agent",
    )
    print("---- demo query schema ----")
    for name, pig_type in loader.get_schema():
        print(f"  {name}: {pig_type}")

    rows = list(loader.load(log_path))
    print(f"---- demo query: {len(rows)} rows, first 3 ----")
    for row in rows[:3]:
        print(f"  {row}")
    return rows


def main():
    fields = fields_mode()
    script = example_mode()
    with tempfile.TemporaryDirectory() as tmp:
        log_path = os.path.join(tmp, "access.log")
        with open(log_path, "w") as f:
            f.write("\n".join(generate_combined_lines(500, seed=11)) + "\n")
        rows = demo_query(log_path)
    return fields, script, rows


if __name__ == "__main__":
    main()
