"""Wordcount-style map/reduce over the batch input format.

Reference behavior: examples/apache-hadoop-mapreduce/.../Wordcount.java — a
Hadoop job that reads an access log through ApacheHttpdLogfileInputFormat and
counts occurrences of one requested field (the user agent).  Here the "job"
runs in-process: one record reader per file split is the map phase (each
reader drives the TPU batch path independently — the same embarrassingly
parallel contract Hadoop provides), and a host-side dict merge is the reduce.
"""
import collections
import os
import tempfile
from typing import Dict

from logparser_tpu.adapters.inputformat import LogfileInputFormat
from logparser_tpu.tools.demolog import generate_combined_lines

FIELD = "HTTP.USERAGENT:request.user-agent"
FIELD_NAME = FIELD.split(":", 1)[1]  # records are keyed by path name


def run_job(log_path: str, split_size: int = 64 * 1024) -> Dict[str, int]:
    input_format = LogfileInputFormat("combined", [FIELD])

    counts: collections.Counter = collections.Counter()
    lines_read = good = bad = 0
    for split in input_format.get_splits(log_path, split_size=split_size):
        # ---- map phase: one reader per split, counting per-key occurrences.
        reader = input_format.create_record_reader(split)
        for _, record in reader:
            ua = record.get_string(FIELD_NAME)
            if ua is not None:
                counts[ua] += 1
        c = reader.counters.as_dict()
        # ---- reduce phase: merge per-split counters.
        lines_read += c["Lines read"]
        good += c["Good lines"]
        bad += c["Bad lines"]
    print(f"Splits processed; lines read={lines_read} good={good} bad={bad}")
    return dict(counts)


def main() -> Dict[str, int]:
    with tempfile.TemporaryDirectory() as tmp:
        log_path = os.path.join(tmp, "access.log")
        with open(log_path, "w") as f:
            f.write("\n".join(generate_combined_lines(2000, seed=7)) + "\n")

        counts = run_job(log_path)

    print("Top user agents:")
    for ua, n in sorted(counts.items(), key=lambda kv: -kv[1])[:5]:
        print(f"  {n:6d}  {ua}")
    return counts


if __name__ == "__main__":
    main()
