"""A custom dissector, loadable dynamically by class path.

Reference behavior: examples/apache-pig/.../UrlClassDissector.java — a
user-written dissector classifying `HTTP.PATH` values into a new
`HTTP.PATH.CLASS:class` output, registered from a Pig script via
``-load:nl.basjes.parse.UrlClassDissector:``.  The equivalent here plugs into
the same demand-driven graph: ask for ``HTTP.PATH.CLASS:...path.class`` and
the compiler wires this dissector behind the URI dissector automatically.
"""
from logparser_tpu.core import Dissector
from logparser_tpu.core.casts import STRING_ONLY


def classify(path_value: str) -> str:
    if path_value.endswith(".html"):
        return "Page"
    if path_value.endswith((".gif", ".png", ".jpg")):
        return "Image"
    if path_value.endswith(".css"):
        return "StyleSheet"
    if path_value.endswith(".js"):
        return "Script"
    if path_value.endswith("_form"):
        return "HackAttempt"
    return "Other"


class UrlClassDissector(Dissector):
    INPUT_TYPE = "HTTP.PATH"

    def initialize_from_settings_parameter(self, settings: str) -> bool:
        return True  # no settings needed; accept the -load: protocol call

    def get_input_type(self) -> str:
        return self.INPUT_TYPE

    def get_possible_output(self):
        return ["HTTP.PATH.CLASS:class"]

    def prepare_for_dissect(self, input_name: str, output_name: str):
        return STRING_ONLY

    def dissect(self, parsable, input_name: str) -> None:
        parsed_field = parsable.get_parsable_field(self.INPUT_TYPE, input_name)
        if parsed_field is None:
            return
        value = parsed_field.value.get_string()
        if not value:
            return
        parsable.add_dissection(input_name, "HTTP.PATH.CLASS", "class", classify(value))
