"""Storm-style bolt embedding.

Reference behavior: examples/apache-storm/.../HttpdLoglineParserBolt.java +
ParserBoltTest.java — a BaseBasicBolt holding a parser; a LocalCluster test
feeds it tuples from a spout and asserts on the emitted records.  Here the
"topology" is an in-process loop: a spout generator, the bolt's
``execute(tuple, collector)``, and a list collector.
"""
from typing import List, Optional

from logparser_tpu.adapters.streaming import ParserConfig, ParserMapOperator
from logparser_tpu.tools.demolog import generate_combined_lines

FIELDS = [
    "IP:connection.client.host",
    "HTTP.USERAGENT:request.user-agent",
]


class ListCollector:
    def __init__(self):
        self.emitted: List[tuple] = []

    def emit(self, values: tuple) -> None:
        self.emitted.append(values)


class HttpdLoglineParserBolt:
    """prepare/execute/declare_output_fields surface over the map operator."""

    def __init__(self, log_format: str, fields: List[str]):
        self._config = ParserConfig(log_format=log_format, fields=fields)
        self._operator: Optional[ParserMapOperator] = None

    def prepare(self) -> None:
        self._operator = ParserMapOperator(self._config)
        self._operator.open()

    def declare_output_fields(self) -> List[str]:
        return list(self._config.fields)

    def execute(self, tup: str, collector: ListCollector) -> None:
        record = self._operator.map(tup)
        if record is not None:
            collector.emit(
                tuple(
                    record.get(f.split(":", 1)[1]) for f in self._config.fields
                )
            )

    def cleanup(self) -> None:
        if self._operator is not None:
            self._operator.close()


def main() -> List[tuple]:
    bolt = HttpdLoglineParserBolt("combined", FIELDS)
    collector = ListCollector()
    bolt.prepare()
    try:
        for line in generate_combined_lines(100, seed=9):  # the "spout"
            bolt.execute(line, collector)
    finally:
        bolt.cleanup()

    print(f"Bolt emitted {len(collector.emitted)} tuples; first 3:")
    for values in collector.emitted[:3]:
        print(f"  {values}")
    return collector.emitted


if __name__ == "__main__":
    main()
