"""Flink-style map-function embedding.

Reference behavior: examples/apache-flink/.../TestParserMapFunctionInline.java
— a RichMapFunction that constructs the parser once in ``open()`` (parsers are
built per worker from serialized string config, never shipped live) and maps
each logline to a record.  ``ParserMapOperator`` is this framework's operator:
``ParserConfig`` is the serializable bit, ``open()`` builds the TPU batch
parser, ``map()`` parses one element.
"""
from typing import List

from logparser_tpu.adapters.streaming import ParserConfig, ParserMapOperator
from logparser_tpu.tools.demolog import generate_combined_lines

FIELDS = [
    "IP:connection.client.host",
    "TIME.EPOCH:request.receive.time.epoch",
    "HTTP.METHOD:request.firstline.method",
    "STRING:request.status.last",
]


def main() -> List:
    config = ParserConfig(log_format="combined", fields=FIELDS)

    # The "task manager" side: open -> map xN -> close.
    operator = ParserMapOperator(config)
    operator.open()
    out = []
    try:
        for line in generate_combined_lines(200, seed=3):
            record = operator.map(line)
            if record is not None:
                out.append(record)
    finally:
        operator.close()

    print(f"Mapped {len(out)} records; first:")
    first = out[0]
    for fid in FIELDS:
        print(f"  {fid} = {first.get(fid.split(':', 1)[1])!r}")
    return out


if __name__ == "__main__":
    main()
