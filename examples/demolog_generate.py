"""Write the synthetic demolog corpus to disk.

Reference counterpart: examples/demolog/hackers-access.log — a 3456-line real
`combined` access log used as demo and bench data.  This repo generates a
deterministic equivalent instead of checking in third-party data; 3456 lines,
seed 42, ~2% hostile/garbage lines to exercise the bad-line path.
"""
import sys

from logparser_tpu.tools.demolog import write_demolog

DEFAULT_LINES = 3456


def main(path: str = "demolog-access.log") -> int:
    n = write_demolog(path, n=DEFAULT_LINES, seed=42, garbage_fraction=0.02)
    print(f"Wrote {n} lines to {path}")
    return n


if __name__ == "__main__":
    main(*sys.argv[1:2])
